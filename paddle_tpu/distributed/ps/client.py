"""PS client: table sharding across servers + pull/push API.

Reference: paddle/fluid/distributed/service/brpc_ps_client.h — dense
params are range-split across servers; sparse rows are sharded by
id % n_servers (reference: SparseShard in table accessor).
"""
import threading

import numpy as np

from .rpc import connect, send_msg, recv_msg


class PSClient:
    def __init__(self, endpoints):
        """endpoints: list of 'host:port' strings."""
        self.endpoints = list(endpoints)
        self._socks = []
        self._locks = []
        self._executor = None
        self._sparse_dims = {}
        for ep in self.endpoints:
            host, port = ep.rsplit(":", 1)
            self._socks.append(connect(host, int(port)))
            self._locks.append(threading.Lock())

    @property
    def n_servers(self):
        return len(self._socks)

    def _call(self, server_idx, req):
        with self._locks[server_idx]:
            send_msg(self._socks[server_idx], req)
            resp = recv_msg(self._socks[server_idx])
        if resp is None:
            raise ConnectionError(
                f"PS server {self.endpoints[server_idx]} closed")
        if not resp.get("ok"):
            raise RuntimeError(resp.get("error", "PS error"))
        return resp

    def _call_parallel(self, reqs):
        """Fan out {server_idx: req} concurrently — one network RTT
        instead of n_servers sequential RTTs (reference: the brpc
        client's async channel fan-out). Returns {server_idx: resp}."""
        if len(reqs) <= 1:
            return {i: self._call(i, r) for i, r in reqs.items()}
        from concurrent.futures import ThreadPoolExecutor
        ex = self._executor
        if ex is None:
            ex = self._executor = ThreadPoolExecutor(
                max_workers=max(2, self.n_servers))
        futs = {i: ex.submit(self._call, i, r) for i, r in reqs.items()}
        return {i: f.result() for i, f in futs.items()}

    def _all(self, req):
        out = self._call_parallel(
            {i: dict(req) for i in range(self.n_servers)})
        return [out[i] for i in range(self.n_servers)]

    # -- dense (replicated per server for simplicity of range bookkeeping:
    # each dense table lives on table_id % n_servers) ----------------------
    def _dense_home(self, table_id):
        # deterministic across processes (python str hash is seeded
        # per-process; every trainer must agree on the home server)
        import zlib
        return zlib.crc32(str(table_id).encode()) % self.n_servers

    def create_dense_table(self, table_id, shape=None, optimizer="sgd",
                           lr=0.01, init=None, seed=0):
        self._call(self._dense_home(table_id), {
            "cmd": "create_dense", "table_id": table_id, "shape": shape,
            "optimizer": optimizer, "lr": lr,
            "init": None if init is None else np.asarray(init),
            "seed": seed})

    def pull_dense(self, table_id):
        return self._call(self._dense_home(table_id),
                          {"cmd": "pull_dense",
                           "table_id": table_id})["value"]

    def push_dense(self, table_id, grad):
        self._call(self._dense_home(table_id),
                   {"cmd": "push_dense", "table_id": table_id,
                    "grad": np.asarray(grad)})

    def set_dense(self, table_id, value):
        self._call(self._dense_home(table_id),
                   {"cmd": "set_dense", "table_id": table_id,
                    "value": np.asarray(value)})

    # -- sparse (rows sharded id % n_servers) ------------------------------
    def create_sparse_table(self, table_id, dim, optimizer="sgd", lr=0.01,
                            seed=0, ssd=False, cache_rows=4096,
                            path=None):
        """ssd=True creates a disk-backed table on each server (reference
        ssd_sparse_table.h): at most cache_rows rows stay in RAM, the
        rest spill to a record file under `path` (server tempdir when
        None)."""
        self._sparse_dims[table_id] = int(dim)
        self._all({"cmd": "create_sparse", "table_id": table_id,
                   "dim": dim, "optimizer": optimizer, "lr": lr,
                   "seed": seed, "ssd": bool(ssd),
                   "cache_rows": int(cache_rows), "path": path})

    def pull_sparse(self, table_id, ids):
        ids = np.asarray(ids).reshape(-1)
        if len(ids) == 0:
            return np.zeros((0, self._sparse_dims.get(table_id, 0)),
                            np.float32)
        reqs, masks = {}, {}
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                reqs[s] = {"cmd": "pull_sparse", "table_id": table_id,
                           "ids": ids[mask]}
                masks[s] = mask
        resps = self._call_parallel(reqs)
        out = np.zeros((len(ids),), dtype=object)
        for s, resp in resps.items():
            out[np.nonzero(masks[s])[0]] = list(resp["rows"])
        return np.stack(list(out), axis=0).astype(np.float32)

    def push_sparse(self, table_id, ids, grads):
        ids = np.asarray(ids).reshape(-1)
        if len(ids) == 0:
            return
        grads = np.asarray(grads, np.float32).reshape(len(ids), -1)
        reqs = {}
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                reqs[s] = {"cmd": "push_sparse", "table_id": table_id,
                           "ids": ids[mask], "grads": grads[mask]}
        self._call_parallel(reqs)

    # -- graph service (GNN; reference graph_brpc_client.h) ----------------
    def create_graph_table(self, table_id, feat_dim=0, seed=0):
        self._graph_feat_dims = getattr(self, "_graph_feat_dims", {})
        self._graph_feat_dims[table_id] = int(feat_dim)
        self._all({"cmd": "create_graph", "table_id": table_id,
                   "feat_dim": feat_dim, "seed": seed})

    def graph_add_edges(self, table_id, src, dst, weights=None):
        src = np.asarray(src).reshape(-1)
        dst = np.asarray(dst).reshape(-1)
        w = np.asarray(weights, np.float32).reshape(-1) \
            if weights is not None else None
        reqs = {}
        for s in range(self.n_servers):
            mask = (src % self.n_servers) == s
            if mask.any():
                reqs[s] = {"cmd": "graph_add_edges",
                           "table_id": table_id, "src": src[mask],
                           "dst": dst[mask],
                           "weights": None if w is None else w[mask]}
        self._call_parallel(reqs)

    def graph_set_node_feat(self, table_id, ids, feats):
        ids = np.asarray(ids).reshape(-1)
        feats = np.asarray(feats, np.float32).reshape(len(ids), -1)
        reqs = {}
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                reqs[s] = {"cmd": "graph_set_feat", "table_id": table_id,
                           "ids": ids[mask], "feats": feats[mask]}
        self._call_parallel(reqs)

    def graph_get_node_feat(self, table_id, ids):
        ids = np.asarray(ids).reshape(-1)
        dim = getattr(self, "_graph_feat_dims", {}).get(table_id, 0)
        if len(ids) == 0:
            return np.zeros((0, dim), np.float32)
        reqs, masks = {}, {}
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                reqs[s] = {"cmd": "graph_get_feat", "table_id": table_id,
                           "ids": ids[mask]}
                masks[s] = mask
        resps = self._call_parallel(reqs)
        out = np.zeros((len(ids),), dtype=object)
        for s, resp in resps.items():
            out[np.nonzero(masks[s])[0]] = list(resp["feats"])
        return np.stack(list(out), axis=0).astype(np.float32)

    def graph_sample_neighbors(self, table_id, ids, count):
        """[len(ids), count] sampled neighbor ids; -1 pads isolated
        nodes. Rows are sharded to each src node's home server."""
        ids = np.asarray(ids).reshape(-1)
        if len(ids) == 0:
            return np.zeros((0, count), np.int64)
        reqs, masks = {}, {}
        for s in range(self.n_servers):
            mask = (ids % self.n_servers) == s
            if mask.any():
                reqs[s] = {"cmd": "graph_sample", "table_id": table_id,
                           "ids": ids[mask], "count": count}
                masks[s] = mask
        resps = self._call_parallel(reqs)
        out = np.full((len(ids), count), -1, np.int64)
        for s, resp in resps.items():
            out[np.nonzero(masks[s])[0]] = resp["neighbors"]
        return out

    def graph_random_nodes(self, table_id, count):
        resps = self._call_parallel(
            {s: {"cmd": "graph_random_nodes", "table_id": table_id,
                 "count": count} for s in range(self.n_servers)})
        pool = np.concatenate([r["nodes"] for r in resps.values()])
        # shuffle before truncating: a plain [:count] would sample only
        # from the first server's shard (even ids), biasing random walks
        return np.random.default_rng().permutation(pool)[:count]

    # -- global shuffle exchange ------------------------------------------
    def shuffle_put(self, dest, blobs):
        """Deposit sample blobs for `dest` rank (bucket homed on server
        dest % n_servers)."""
        self._call(dest % self.n_servers,
                   {"cmd": "shuffle_put", "dest": dest, "blobs": blobs})

    def shuffle_take(self, rank):
        return self._call(rank % self.n_servers,
                          {"cmd": "shuffle_take", "rank": rank})["blobs"]

    # -- control -----------------------------------------------------------
    def barrier(self, n_trainers):
        """Global barrier across trainers via server 0 (reference:
        BarrierTable)."""
        self._call(0, {"cmd": "barrier", "trainers": n_trainers})

    def save(self, path):
        self._call_parallel({i: {"cmd": "save",
                                 "path": f"{path}.server{i}"}
                             for i in range(self.n_servers)})

    def load(self, path):
        self._call_parallel({i: {"cmd": "load",
                                 "path": f"{path}.server{i}"}
                             for i in range(self.n_servers)})

    def ping(self):
        return self._all({"cmd": "ping"})

    def stop_servers(self):
        for i in range(self.n_servers):
            try:
                self._call(i, {"cmd": "stop"})
            except (ConnectionError, OSError):
                pass

    def close(self):
        if self._executor is not None:
            self._executor.shutdown(wait=False)
            self._executor = None
        for s in self._socks:
            try:
                s.close()
            except OSError:
                pass
