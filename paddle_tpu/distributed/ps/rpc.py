"""Tiny length-prefixed pickle RPC (the brpc stand-in).

Reference: paddle/fluid/distributed/service/sendrecv.proto message
framing + brpc channel. One request/response per connection round; the
client keeps a persistent socket per server.
"""
import pickle
import socket
import struct

_HDR = struct.Struct("!Q")


def send_msg(sock, obj):
    payload = pickle.dumps(obj, protocol=4)
    sock.sendall(_HDR.pack(len(payload)) + payload)


def recv_msg(sock):
    hdr = _recv_exact(sock, _HDR.size)
    if hdr is None:
        return None
    (n,) = _HDR.unpack(hdr)
    body = _recv_exact(sock, n)
    if body is None:
        return None
    return pickle.loads(body)


def _recv_exact(sock, n):
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def connect(host, port, timeout=30.0, retry_secs=60.0):
    """Connect with readiness retries: trainers routinely start before
    their servers have bound (reference: test_collective_base.py:37
    waits for endpoint readiness)."""
    import time
    deadline = time.time() + retry_secs
    while True:
        try:
            sock = socket.create_connection((host, port), timeout=timeout)
            break
        except (ConnectionRefusedError, OSError):
            if time.time() >= deadline:
                raise
            time.sleep(0.3)
    # blocking after connect: a receive timeout mid-request (e.g. a long
    # barrier wait) would desync the length-prefixed stream — the late
    # response would be read as the reply to the NEXT request
    sock.settimeout(None)
    sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return sock
