"""Collective communication API.

Reference parity: python/paddle/distributed/collective.py (broadcast:348,
all_reduce:415, reduce:495, all_gather:589, scatter:667, alltoall,
barrier:167) over the reference's c_* NCCL ops
(paddle/fluid/operators/collective/). TPU-native mapping (SURVEY §5):

    c_allreduce_sum  -> lax.psum       over a mesh axis
    c_reducescatter  -> lax.psum_scatter
    c_allgather      -> lax.all_gather
    send_v2/recv_v2  -> lax.ppermute
    alltoall         -> lax.all_to_all

A Group names a mesh axis (ring_id -> axis name). Collectives are valid in
two contexts:
  1. inside an SPMD region (shard_map / pjit manual axes) — lowers to the
     XLA collective on ICI;
  2. eagerly on a Tensor — executed via a one-op shard_map over the
     group's mesh so single-controller eager code sees paddle semantics
     (the tensor's leading-axis shards are the "per-rank" values).
If the group spans a single device, collectives are identities, matching
single-process paddle.
"""
import jax
from ..core.jax_compat import shard_map as _shard_map
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core.tensor import Tensor
from ..core.dispatch import register_op
from . import topology

_GROUPS = {}
_next_group_id = [1]  # gid 0 is the default group
_default = [None]


class Group:
    """A communication group = a mesh axis (reference: collective.py:79
    Group over NCCL ring ids)."""

    def __init__(self, axis=None, mesh=None, ranks=None, gid=None):
        self.axis = axis
        self.mesh = mesh if mesh is not None else topology.get_mesh()
        self.ranks = ranks
        self.id = gid if gid is not None else _next_group_id[0]
        _next_group_id[0] += 1

    @property
    def nranks(self):
        if self.mesh is not None and self.axis in (self.mesh.shape or {}):
            return int(self.mesh.shape[self.axis])
        if self.ranks:
            return len(self.ranks)
        return jax.device_count()

    @property
    def world_size(self):
        return self.nranks

    def __repr__(self):
        return f"Group(axis={self.axis}, nranks={self.nranks})"


def _default_group():
    mesh = topology.get_mesh()
    if mesh is None:
        # implicit flat dp mesh over all devices
        hc = topology.HybridCommunicateGroup(dp=jax.device_count())
        mesh = hc.mesh
    cached = _default[0]
    if cached is None or cached.mesh is not mesh:
        cached = Group(axis="dp", mesh=mesh, gid=0)
        _default[0] = cached
        _GROUPS[0] = cached
    return cached


def new_group(ranks=None, backend=None, timeout=None):
    """Reference: collective.py:209. Creates a group over the given global
    ranks; in the mesh model sub-groups map to mesh axes — a custom rank
    subset gets a dedicated 1-axis mesh over those devices. The group is
    registered so get_group(g.id) finds it again."""
    if ranks is None:
        g = _default_group()
    else:
        devs = jax.devices()
        sub = [devs[r] for r in ranks]
        import numpy as np
        mesh = jax.sharding.Mesh(np.asarray(sub), ("sub",))
        g = Group(axis="sub", mesh=mesh, ranks=list(ranks))
    _GROUPS[g.id] = g
    return g


def get_group(gid=0):
    if gid == 0:
        return _default_group()
    g = _GROUPS.get(gid)
    if g is None:
        from ..core.errors import InvalidArgumentError
        raise InvalidArgumentError(
            f"no group with id {gid}; create it via new_group")
    return g


def _axis_in_scope(axis):
    """True when `axis` is a manual (shard_map) axis in the current trace —
    collectives then lower directly to XLA collectives over ICI."""
    try:
        from jax._src import core as _core
        return axis in _core.unsafe_get_axis_names()
    except Exception:
        return False


_REDUCE_FNS = {
    "sum": jax.lax.psum,
    "max": jax.lax.pmax,
    "min": jax.lax.pmin,
}


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


def _eager_collective(x, group, per_shard_fn, out_spec_fn=None):
    """Run an XLA collective eagerly over the group's mesh axis via a
    one-op shard_map. x is sharded (or replicated) on the leading dim."""
    mesh = group.mesh
    axis = group.axis
    n = int(mesh.shape[axis])
    if n == 1:
        return per_shard_fn(x, single=True)
    in_spec = P(axis)
    out_spec = out_spec_fn(axis) if out_spec_fn is not None else P(axis)
    fn = _shard_map(lambda v: per_shard_fn(v, single=False),
                       mesh=mesh, in_specs=(in_spec,), out_specs=out_spec)
    return fn(x)


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    """paddle.distributed.all_reduce. Inside SPMD: psum over the axis.
    Eager: reduces the per-rank values along the tensor's leading shards;
    single-device groups are identity."""
    g = group or _default_group()
    axis = g.axis
    if isinstance(tensor, Tensor) and _axis_in_scope(axis):
        out = _spmd_allreduce(tensor, axis=axis,
                              op=op if isinstance(op, str) else "sum")
        tensor.value = out.value
        return tensor
    n = g.nranks
    if n == 1:
        return tensor
    red_name = op if isinstance(op, str) else "sum"
    out = _eager_collective(
        tensor.value, g,
        lambda v, single: _reduce_shard(v, axis, red_name, n))
    tensor.value = out
    return tensor


def _reduce_shard(v, axis, red_name, n):
    """Per-shard reduction body (runs inside shard_map)."""
    if red_name == "avg":
        return jax.lax.psum(v, axis) / n
    if red_name == "prod":
        # no pprod primitive in lax: gather the n shard values and take
        # the product (log-psum would break on zeros/negatives)
        g_all = jax.lax.all_gather(v, axis)
        return jnp.prod(g_all, axis=0)
    return _REDUCE_FNS.get(red_name, jax.lax.psum)(v, axis)


@register_op("c_allreduce", differentiable=True)
def _spmd_allreduce(x, *, axis, op):
    if op == "sum":
        return jax.lax.psum(x, axis)
    if op == "max":
        return jax.lax.pmax(x, axis)
    if op == "min":
        return jax.lax.pmin(x, axis)
    if op == "avg":
        return jax.lax.pmean(x, axis)
    if op == "prod":
        return jnp.prod(jax.lax.all_gather(x, axis), axis=0)
    raise ValueError(op)


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    g = group or _default_group()
    n = g.nranks
    if _axis_in_scope(g.axis):
        gathered = _spmd_allgather(tensor, axis=g.axis)
        from ..ops import manipulation
        tensor_list.extend(manipulation.unbind(gathered, axis=0))
        return tensor_list
    if n == 1:
        tensor_list.append(tensor)
        return tensor_list
    # Eager single-controller: the tensor's shards along the group axis are
    # the per-rank values; gather them to host-visible tensors. A leading
    # dim that does not divide the group size has no per-rank meaning —
    # silently replicating would be a wrong result.
    v = jnp.asarray(tensor.value)
    if v.ndim == 0 or v.shape[0] % n != 0:
        raise ValueError(
            f"all_gather: leading dim of shape {tuple(v.shape)} is not "
            f"divisible by group size {n}; eager collectives treat the "
            "leading-axis shards as the per-rank values")
    tensor_list.extend(Tensor(s) for s in jnp.split(v, n, axis=0))
    return tensor_list


@register_op("c_allgather", differentiable=False)
def _spmd_allgather(x, *, axis):
    return jax.lax.all_gather(x, axis)


def broadcast(tensor, src=0, group=None, sync_op=True):
    """Single-controller: all mesh shards already share the controller's
    value for replicated tensors; for sharded tensors broadcast copies the
    src shard to all shards."""
    g = group or _default_group()
    n = g.nranks
    if n == 1 or not isinstance(tensor, Tensor):
        return tensor

    def shard_fn(v, single):
        g_all = jax.lax.all_gather(v, g.axis)
        return g_all[src]

    out = _eager_collective(tensor.value, g, shard_fn)
    tensor.value = out
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):  # noqa: A001
    """paddle.distributed.reduce: only rank `dst` receives the reduction;
    other ranks keep their input (reference collective.py:495). Inside an
    SPMD region dst semantics collapse (every program instance is the same
    program) and this is an all_reduce; eagerly the dst *shard* gets the
    reduced value and the other shards are left unchanged."""
    g = group or _default_group()
    if _axis_in_scope(g.axis):
        return all_reduce(tensor, op, group, sync_op)
    n = g.nranks
    if n == 1:
        return tensor
    # dst is a GLOBAL rank; convert to the group-local index the axis
    # compares against (reference: group.get_group_rank(dst))
    if g.ranks is not None:
        if dst not in g.ranks:
            raise ValueError(f"reduce: dst rank {dst} not in group "
                             f"{g.ranks}")
        dst_local = g.ranks.index(dst)
    else:
        if not 0 <= dst < n:
            raise ValueError(f"reduce: dst rank {dst} out of range for "
                             f"group of size {n}")
        dst_local = dst
    red_name = op if isinstance(op, str) else "sum"
    axis = g.axis

    def shard_fn(v, single):
        red = _reduce_shard(v, axis, red_name, n)
        idx = jax.lax.axis_index(axis)
        return jnp.where(idx == dst_local, red, v)

    out = _eager_collective(tensor.value, g, shard_fn)
    tensor.value = out
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g = group or _default_group()
    if g.nranks == 1:
        if tensor_list:
            tensor.value = tensor_list[0].value
        return tensor
    # Single-controller: scatter = shard the stacked list over the group
    # axis; the receiving "rank's" view is the sharded array itself.
    from ..ops import manipulation
    from jax.sharding import NamedSharding, PartitionSpec
    stacked = manipulation.concat(tensor_list, axis=0)
    sharded = jax.device_put(stacked.value,
                             NamedSharding(g.mesh, PartitionSpec(g.axis)))
    tensor.value = sharded
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    g = group or _default_group()
    n = g.nranks
    if _axis_in_scope(g.axis):
        from ..ops import manipulation
        stacked = manipulation.stack(in_tensor_list, axis=0)
        out = _spmd_alltoall(stacked, axis=g.axis)
        outs = manipulation.unbind(out, axis=0)
        if out_tensor_list is not None:
            out_tensor_list.extend(outs)
            return out_tensor_list
        return outs
    if n == 1:
        if out_tensor_list is not None:
            out_tensor_list.extend(in_tensor_list)
            return out_tensor_list
        return list(in_tensor_list)
    # Eager single-controller (reference: imperative alltoall is an eager
    # op — paddle/fluid/imperative eager collectives): each tensor's
    # leading-axis blocks are the per-rank values; out[j] block r =
    # in[r] block j. One shard_map'd lax.all_to_all over the slot axis
    # does the exchange on ICI.
    if len(in_tensor_list) != n:
        raise ValueError(
            f"alltoall: need exactly {n} input tensors (one per rank), "
            f"got {len(in_tensor_list)}")
    vals = [jnp.asarray(t.value if isinstance(t, Tensor) else t)
            for t in in_tensor_list]
    if vals[0].ndim == 0 or vals[0].shape[0] % n != 0:
        raise ValueError(
            f"alltoall: leading dim of shape {tuple(vals[0].shape)} is "
            f"not divisible by group size {n}; eager collectives treat "
            "the leading-axis blocks as the per-rank values")
    stacked = jnp.stack(vals, axis=1)  # [B, n_slots, ...]
    axis = g.axis
    out = _eager_collective(
        stacked, g,
        lambda v, single: jax.lax.all_to_all(
            v, axis, split_axis=1, concat_axis=1, tiled=False))
    outs = [Tensor(out[:, j]) for j in range(n)]
    if out_tensor_list is not None:
        out_tensor_list.extend(outs)
        return out_tensor_list
    return outs


@register_op("c_alltoall", differentiable=True)
def _spmd_alltoall(x, *, axis):
    return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=False)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    g = group or _default_group()
    if _axis_in_scope(g.axis):
        from ..ops import manipulation
        stacked = manipulation.stack(tensor_list, axis=0) \
            if tensor_list is not None else tensor
        out = _spmd_reduce_scatter(stacked, axis=g.axis)
        tensor.value = out.value
        return tensor
    if g.nranks == 1:
        if tensor_list:
            tensor.value = tensor_list[0].value
        return tensor
    # Eager single-controller: rank r's output = reduce over ranks j of
    # (rank j's tensor_list[r]); with leading-axis blocks as per-rank
    # values this is one shard_map'd psum_scatter (SUM fast path) or an
    # all_gather + local reduction (other ops) over the slot axis.
    n = g.nranks
    axis = g.axis
    red_name = op if isinstance(op, str) else "sum"

    def _scatter_reduce(v, scatter_dim):
        # v per-device: slot dim `scatter_dim` has size n; keep column
        # axis_index after reducing over ranks
        if red_name == "sum":
            return jax.lax.psum_scatter(v, axis,
                                        scatter_dimension=scatter_dim,
                                        tiled=False)
        g_all = jax.lax.all_gather(v, axis)      # [n_ranks, ...local...]
        idx = jax.lax.axis_index(axis)
        mine = jnp.take(g_all, idx, axis=1 + scatter_dim)  # my column
        if red_name == "max":
            return jnp.max(mine, axis=0)
        if red_name == "min":
            return jnp.min(mine, axis=0)
        if red_name == "prod":
            return jnp.prod(mine, axis=0)
        if red_name == "avg":
            return jnp.mean(mine, axis=0)
        raise ValueError(f"unknown reduce op {red_name!r}")

    if tensor_list is not None:
        if len(tensor_list) != n:
            raise ValueError(
                f"reduce_scatter: need exactly {n} input tensors (one "
                f"per rank), got {len(tensor_list)}")
        vals = [jnp.asarray(t.value if isinstance(t, Tensor) else t)
                for t in tensor_list]
        if vals[0].ndim == 0 or vals[0].shape[0] % n != 0:
            raise ValueError(
                f"reduce_scatter: leading dim of shape "
                f"{tuple(vals[0].shape)} is not divisible by group size "
                f"{n}; eager collectives treat the leading-axis blocks "
                "as the per-rank values")
        stacked = jnp.stack(vals, axis=1)  # [B, n_slots, ...]
        tensor.value = _eager_collective(
            stacked, g, lambda v, single: _scatter_reduce(v, 1))
        return tensor
    # single-input form: each rank's block is split n ways and scattered
    v = jnp.asarray(tensor.value)
    if v.ndim == 0 or v.shape[0] % (n * n) != 0:
        raise ValueError(
            f"reduce_scatter: leading dim of shape {tuple(v.shape)} must "
            f"divide by group_size^2 ({n * n}) in single-tensor eager "
            "form (each per-rank block is split n ways)")
    tensor.value = _eager_collective(
        v, g,
        lambda s, single: _scatter_reduce(
            s.reshape((n, s.shape[0] // n) + s.shape[1:]), 0))
    return tensor


@register_op("c_reducescatter", differentiable=True)
def _spmd_reduce_scatter(x, *, axis):
    return jax.lax.psum_scatter(x, axis, scatter_dimension=0, tiled=False)


def barrier(group=None):
    """XLA executions are ordered per device; a controller-level barrier is
    a device sync (reference: barrier op -> here effects_barrier)."""
    jax.effects_barrier()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        v = tensor.value
        if hasattr(v, "block_until_ready"):
            v.block_until_ready()
    return tensor


def get_rank(group=None):
    from . import env
    return env.get_rank()


def get_world_size(group=None):
    from . import env
    return env.get_world_size()


def is_initialized():
    return True


# --- TP helper primitives (reference: collective.py:748-921 _c_identity,
# _c_concat, _c_split, _mp_allreduce, _c_lookup_table) -----------------------

@register_op("c_identity_op")
def _c_identity_impl(x, *, axis):
    # forward identity; backward all-reduces over the mp axis — implemented
    # via custom vjp so the autograd tape gets the psum on the grad path.
    @jax.custom_vjp
    def ident(v):
        return v

    def fwd(v):
        return v, None

    def bwd(_, g):
        return (jax.lax.psum(g, axis),)
    ident.defvjp(fwd, bwd)
    return ident(x)


def _c_identity(tensor, group=None):
    g = group or _default_group()
    if not _axis_in_scope(g.axis):
        return tensor
    return _c_identity_impl(tensor, axis=g.axis)


@register_op("mp_allreduce_op")
def _mp_allreduce_impl(x, *, axis):
    # forward allreduce; backward identity (reference c_allreduce with
    # use_model_parallel=True)
    @jax.custom_vjp
    def ar(v):
        return jax.lax.psum(v, axis)

    def fwd(v):
        return jax.lax.psum(v, axis), None

    def bwd(_, g):
        return (g,)
    ar.defvjp(fwd, bwd)
    return ar(x)


def _mp_allreduce(tensor, op=ReduceOp.SUM, group=None,
                  use_calc_stream=True, use_model_parallel=True):
    g = group or _default_group()
    if not _axis_in_scope(g.axis):
        return tensor
    return _mp_allreduce_impl(tensor, axis=g.axis)


@register_op("send_recv_shift", differentiable=True)
def _ppermute_shift(x, *, axis, perm):
    return jax.lax.ppermute(x, axis, perm=list(perm))


def send(tensor, dst=0, group=None, sync_op=True, src=0):
    """Reference: collective.py send (send_v2 NCCL p2p). SPMD form: one
    ppermute edge src->dst (both ends named — every rank executes the
    same program); the destination rank receives the value, other ranks
    zeros. Eager single-controller: the value is staged on the group so
    the matching recv returns it (loopback, same process)."""
    g = group or _default_group()
    if _axis_in_scope(g.axis):
        n = g.nranks
        return _ppermute_shift(tensor, axis=g.axis,
                               perm=((src % n, dst % n),))
    _P2P_STAGE.setdefault(id(g) if g.id == 0 else g.id, []).append(
        tensor)
    return tensor


def recv(tensor, src=0, group=None, sync_op=True, dst=None):
    """Reference: collective.py recv (recv_v2). Inside an SPMD region a
    p2p edge must name BOTH ends (every rank runs the same program, so
    'the current rank' is not a static quantity): pass dst=. The
    destination rank's buffer gets src's value; other ranks get zeros
    (recv_v2 overwrites only the destination buffer). For uniform
    neighbor exchange use the pipeline/ppermute APIs instead."""
    g = group or _default_group()
    if _axis_in_scope(g.axis):
        if dst is None:
            from ..core.errors import InvalidArgumentError
            raise InvalidArgumentError(
                "recv inside an SPMD region needs dst= (the receiving "
                "rank); a single-program p2p edge must name both ends")
        n = g.nranks
        out = _ppermute_shift(tensor, axis=g.axis,
                              perm=((src % n, dst % n),))
        tensor.value = out.value
        return tensor
    staged = _P2P_STAGE.get(id(g) if g.id == 0 else g.id, [])
    if staged:
        tensor.value = staged.pop(0).value
    return tensor


_P2P_STAGE = {}


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference: collective.py split — auto-sharded layer factory
    (parallel linear / embedding over the mp axis). TPU-native: build
    the matching Megatron TP layer and apply it."""
    from .fleet.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding)
    if operation == "linear":
        in_f, out_f = size
        if axis == 1:
            layer = ColumnParallelLinear(in_f, out_f,
                                         gather_output=gather_out,
                                         weight_attr=weight_attr,
                                         has_bias=bias_attr is not False)
        else:
            layer = RowParallelLinear(in_f, out_f,
                                      input_is_parallel=False,
                                      weight_attr=weight_attr,
                                      has_bias=bias_attr is not False)
        return layer(x)
    if operation == "embedding":
        vocab, dim = size
        layer = VocabParallelEmbedding(vocab, dim,
                                       weight_attr=weight_attr)
        return layer(x)
    raise ValueError(f"unknown split operation {operation!r}")
