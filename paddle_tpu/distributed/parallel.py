"""Data parallelism.

Reference parity: python/paddle/distributed/parallel.py:58
init_parallel_env + python/paddle/fluid/dygraph/parallel.py:382
DataParallel over the C++ Reducer (paddle/fluid/imperative/reducer.cc).

TPU-native design: there is no bucketed-allreduce Reducer. Data parallelism
is a sharding: inputs are sharded over the mesh 'dp' axis, parameters are
replicated, and XLA inserts the gradient all-reduce automatically when the
backward contraction crosses the sharded batch dimension (GSPMD). This
subsumes the Reducer's overlap behavior — XLA's latency-hiding scheduler
overlaps the psum with remaining backward compute. `DataParallel` is
therefore a thin wrapper that (a) ensures a mesh exists, (b) shards inputs
over 'dp', (c) replicates parameters.
"""
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from . import topology


def init_parallel_env():
    """Reference: distributed/parallel.py:58. Multi-host: initialize the
    JAX distributed runtime from launcher-provided env vars; single host:
    create the default dp mesh over local devices."""
    import os
    if "PADDLE_COORDINATOR" in os.environ:
        jax.distributed.initialize(
            coordinator_address=os.environ["PADDLE_COORDINATOR"],
            num_processes=int(os.environ.get("PADDLE_TRAINERS_NUM", "1")),
            process_id=int(os.environ.get("PADDLE_TRAINER_ID", "0")))
    if topology.get_mesh() is None:
        topology.HybridCommunicateGroup(dp=jax.device_count())
    from .env import ParallelEnv
    return ParallelEnv()


def _dp_sharding(mesh, ndim):
    return NamedSharding(mesh, P(*(("dp",) + (None,) * (ndim - 1))))


def _replicated(mesh):
    return NamedSharding(mesh, P())


class DataParallel(Layer):
    """paddle.DataParallel wrapper (reference: fluid/dygraph/parallel.py:382).

    Shards batch inputs over the 'dp' mesh axis and replicates parameters.
    Under a compiled train step (to_static) GSPMD partitions the whole step;
    eagerly, jax follows input shardings per op. Gradient averaging matches
    the reference (mean loss over the global batch <=> grad mean)."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        if topology.get_mesh() is None:
            init_parallel_env()
        self._mesh = topology.get_mesh()

    def scale_batch(self, x):
        """Annotate a global-batch tensor as dp-sharded (materializes when
        the step compiles; eager stays single-device by design)."""
        from .fleet.meta_parallel.mp_layers import shard_constraint
        if isinstance(x, Tensor):
            return shard_constraint(x, ("dp",) + (None,) * (x.ndim - 1),
                                    mesh=self._mesh)
        return x

    def forward(self, *inputs, **kwargs):
        sharded = []
        for x in inputs:
            if isinstance(x, Tensor) and x.ndim >= 1 and \
                    x.shape[0] % int(self._mesh.shape["dp"]) == 0:
                sharded.append(self.scale_batch(x))
            else:
                sharded.append(x)
        return self._layers(*sharded, **kwargs)

    # delegate everything stateful to the wrapped layer
    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def train(self):
        self._layers.train()
        self.training = True
        return self

    def eval(self):
        self._layers.eval()
        self.training = False
        return self

    def no_sync(self):
        import contextlib
        return contextlib.nullcontext()


def get_rank():
    from .env import get_rank as _r
    return _r()


def get_world_size():
    from .env import get_world_size as _w
    return _w()
