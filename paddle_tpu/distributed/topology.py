"""Hybrid-parallel topology over a jax.sharding.Mesh.

TPU-native equivalent of the reference 4-axis topology (reference:
python/paddle/distributed/fleet/base/topology.py:36 CommunicateTopology,
:117 HybridCommunicateGroup). The reference builds per-axis NCCL comm
groups from a cartesian rank layout; here the cartesian layout IS a
jax.sharding.Mesh with named axes, and "communication groups" are mesh
axis names consumed by XLA collectives. A fifth axis `sp` (sequence/
context parallel) is first-class — absent in the reference (SURVEY §5),
greenfield here.

Axis order (outer->inner): pp, dp, sharding, sp, mp — neighboring mp ranks
land on adjacent devices (ICI neighbors), matching the reference's
guidance that tensor-parallel traffic needs the fastest links.
"""
import numpy as np
import jax
from jax.sharding import Mesh

_HYBRID = None  # global HybridCommunicateGroup


AXES = ("pp", "dp", "sharding", "sp", "mp")


def build_mesh(dp=1, mp=1, pp=1, sharding=1, sp=1, devices=None):
    devices = devices if devices is not None else jax.devices()
    need = dp * mp * pp * sharding * sp
    if need != len(devices):
        if need == 1:
            dp = len(devices)
            need = len(devices)
        else:
            raise ValueError(
                f"product of parallel degrees {need} != device count "
                f"{len(devices)}")
    arr = np.asarray(devices).reshape(pp, dp, sharding, sp, mp)
    return Mesh(arr, AXES)


class HybridCommunicateGroup:
    """Reference: topology.py:117 — exposes rank/degree accessors per axis.
    In the SPMD model there is no per-process 'my rank in group'; the
    accessors report degrees and mesh handles used to build shardings."""

    def __init__(self, strategy=None, mesh=None, dp=1, mp=1, pp=1,
                 sharding=1, sp=1):
        if strategy is not None:
            hc = strategy.hybrid_configs
            dp = hc.get("dp_degree", 1)
            mp = hc.get("mp_degree", 1)
            pp = hc.get("pp_degree", 1)
            sharding = hc.get("sharding_degree", 1)
            sp = hc.get("sp_degree", hc.get("sep_degree", 1))
        self._dp_degree = dp
        self._mp_degree = mp
        self._pp_degree = pp
        self._sharding_degree = sharding
        self._sp_degree = sp
        self.mesh = mesh if mesh is not None else build_mesh(
            dp=dp, mp=mp, pp=pp, sharding=sharding, sp=sp)
        global _HYBRID
        _HYBRID = self

    # degrees ------------------------------------------------------------
    def get_data_parallel_world_size(self):
        return int(self.mesh.shape["dp"])

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sequence_parallel_world_size(self):
        return self._sp_degree

    # group handles (mesh axis names) ------------------------------------
    def get_data_parallel_group(self):
        from .collective import Group
        return Group(axis="dp", mesh=self.mesh)

    def get_model_parallel_group(self):
        from .collective import Group
        return Group(axis="mp", mesh=self.mesh)

    def get_pipe_parallel_group(self):
        from .collective import Group
        return Group(axis="pp", mesh=self.mesh)

    def get_sharding_parallel_group(self):
        from .collective import Group
        return Group(axis="sharding", mesh=self.mesh)

    def get_sequence_parallel_group(self):
        from .collective import Group
        return Group(axis="sp", mesh=self.mesh)

    # reference-compat rank accessors (SPMD: controller sees all ranks) --
    def get_global_rank(self):
        return 0

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_rank_from_stage(self, stage_id, **kwargs):
        return stage_id

    def topology(self):
        return self.mesh


def get_hybrid_communicate_group():
    return _HYBRID


def get_mesh():
    if _HYBRID is not None:
        return _HYBRID.mesh
    return None


def set_mesh(mesh):
    global _HYBRID
    if _HYBRID is None:
        hc = HybridCommunicateGroup.__new__(HybridCommunicateGroup)
        hc._dp_degree = int(mesh.shape.get("dp", 1))
        hc._mp_degree = int(mesh.shape.get("mp", 1))
        hc._pp_degree = int(mesh.shape.get("pp", 1))
        hc._sharding_degree = int(mesh.shape.get("sharding", 1))
        hc._sp_degree = int(mesh.shape.get("sp", 1))
        hc.mesh = mesh
        _HYBRID = hc
    else:
        _HYBRID.mesh = mesh
    return _HYBRID


class CommunicateTopology:
    """Reference: topology.py:36 — cartesian coordinate helper."""

    def __init__(self, hybrid_group_names=("data", "pipe", "sharding",
                                           "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = None
        self._world = int(np.prod(dims))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return self._world

    def get_rank(self, **kwargs):
        coord = [kwargs[n] for n in self._parallel_names]
        return int(np.ravel_multi_index(coord, self._dims))

    def get_coord(self, rank):
        return tuple(int(c) for c in np.unravel_index(rank, self._dims))

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        ranks = []
        for r in range(self._world):
            if self.get_coord(r)[axis] == index:
                ranks.append(r)
        return ranks

    def get_dim_size(self, axis_name):
        return self.get_dim(axis_name)

    def get_comm_list(self, axis_name):
        axis = self._parallel_names.index(axis_name)
        others = [i for i in range(len(self._dims)) if i != axis]
        comm_list = []
        for combo in np.ndindex(*[self._dims[i] for i in others]):
            group = []
            for k in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, o in enumerate(others):
                    coord[o] = combo[i]
                coord[axis] = k
                group.append(int(np.ravel_multi_index(coord, self._dims)))
            comm_list.append(group)
        return comm_list
