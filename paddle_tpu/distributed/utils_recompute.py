"""Activation recompute (gradient checkpointing).

Reference parity: python/paddle/distributed/fleet/utils/recompute.py:63
RecomputeFunction — a PyLayer that drops intermediate activations in
forward and replays the forward during backward with preserved RNG state.
TPU-native: same PyLayer structure; RNG preservation snapshots the global
generator key (functional keys make exact replay trivial — no
cuda RNG state juggling like the reference's :171).
"""
from ..autograd import PyLayer
from ..core.tensor import Tensor
from ..core import rng as rng_mod
from ..core.dispatch import no_grad, enable_grad


class RecomputeFunction(PyLayer):
    @staticmethod
    def forward(ctx, run_function, preserve_rng_state, *args):
        ctx.run_function = run_function
        ctx.preserve_rng_state = preserve_rng_state
        if preserve_rng_state:
            ctx.rng_state = rng_mod.default_generator.state.value
        ctx.inputs = args
        with no_grad():
            outputs = run_function(*args)
        return outputs

    @staticmethod
    def backward(ctx, *grads):
        # replay forward with grad tracking, then backward through it
        detached = []
        for a in ctx.inputs:
            if isinstance(a, Tensor):
                d = a.detach()
                d.stop_gradient = a.stop_gradient
                detached.append(d)
            else:
                detached.append(a)
        if ctx.preserve_rng_state:
            saved = rng_mod.default_generator.state.value
            rng_mod.default_generator.state.value = ctx.rng_state
        try:
            with enable_grad():
                outputs = ctx.run_function(*detached)
        finally:
            if ctx.preserve_rng_state:
                rng_mod.default_generator.state.value = saved
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        gs = list(grads)
        from ..core.engine import run_backward
        for o, g in zip(outs, gs):
            if isinstance(o, Tensor) and not o.stop_gradient:
                run_backward(o, g, retain_graph=True)
        # one grad slot per Tensor input of apply(), aligned with the
        # engine's node.input_tensors (None for stop_gradient inputs)
        results = []
        for d in detached:
            if isinstance(d, Tensor):
                results.append(d._grad if d._grad is not None else None)
        return tuple(results) if len(results) > 1 else results[0]


def recompute(function, *args, **kwargs):
    """fleet.utils.recompute(fn, *args). preserve_rng_state kwarg honored."""
    preserve = kwargs.pop("preserve_rng_state", True)
    if kwargs:
        raise ValueError(f"unexpected kwargs {list(kwargs)}")
    # if no grad needed, just run
    from ..core.dispatch import is_grad_enabled
    if not is_grad_enabled() or not any(
            isinstance(a, Tensor) and not a.stop_gradient for a in args):
        return function(*args)
    return RecomputeFunction.apply(function, preserve, *args)
