"""CLI entry matching the reference `python -m paddle.distributed.launch`
(reference: python/paddle/distributed/fleet/launch.py:396). Forwards to
launch_mod.launch()."""
from .launch_mod import launch

if __name__ == "__main__":
    launch()
