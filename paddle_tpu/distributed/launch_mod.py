"""Launcher.

Reference parity: python/paddle/distributed/fleet/launch.py:396 (process
launcher setting PADDLE_TRAINER_ID/ENDPOINTS per proc) and
python/paddle/distributed/spawn.py.

TPU-native: one controller process normally drives all local chips, so
`spawn(fn)` simply runs fn — per-DEVICE processes are not a thing here.
Multi-CONTROLLER runs are: `--nproc_per_node N` spawns N processes that
jax.distributed.initialize against a coordinator (loopback by default;
combine with --coordinator/--nnodes/--node_rank for multi-host), each
seeing the global device set. `--server_num/--worker_num` spawns a local
parameter-server cluster instead.
"""
import os
import runpy
import sys


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs not in (1, -1):
        raise RuntimeError(
            "paddle_tpu uses single-controller SPMD: one process drives "
            "all chips. Express device parallelism with fleet "
            "hybrid_configs / Mesh, or launch a multi-controller run "
            "with `python -m paddle_tpu.distributed.launch_mod "
            "--nproc_per_node N script.py`.")
    return func(*args)


def _free_port():
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch_ps_cluster(server_num, worker_num, script, script_args):
    """Reference: fleet/launch.py PS mode — spawn server processes
    (TRAINING_ROLE=PSERVER, POD_IP/PADDLE_PORT) and worker processes
    (TRAINING_ROLE=TRAINER, PADDLE_TRAINER_ID), all sharing
    PADDLE_PSERVERS_IP_PORT_LIST / PADDLE_TRAINER_ENDPOINTS."""
    import subprocess
    server_eps = [f"127.0.0.1:{_free_port()}" for _ in range(server_num)]
    worker_eps = [f"127.0.0.1:{_free_port()}" for _ in range(worker_num)]
    base = dict(os.environ)
    base["PADDLE_PSERVERS_IP_PORT_LIST"] = ",".join(server_eps)
    base["PADDLE_TRAINER_ENDPOINTS"] = ",".join(worker_eps)
    base["PADDLE_TRAINERS_NUM"] = str(worker_num)
    procs = []
    for i, ep in enumerate(server_eps):
        env = dict(base)
        ip, port = ep.rsplit(":", 1)
        env.update(TRAINING_ROLE="PSERVER", POD_IP=ip, PADDLE_PORT=port)
        procs.append(("server", subprocess.Popen(
            [sys.executable, script] + script_args, env=env)))
    for i in range(worker_num):
        env = dict(base)
        env.update(TRAINING_ROLE="TRAINER", PADDLE_TRAINER_ID=str(i))
        procs.append(("worker", subprocess.Popen(
            [sys.executable, script] + script_args, env=env)))
    # reference launcher semantics: wait for workers; servers are
    # terminated when training finishes
    rc = 0
    for kind, p in procs:
        if kind == "worker":
            rc = p.wait() or rc
    _reap([p for kind, p in procs if kind == "server"])
    return rc


def _reap(procs):
    """SIGTERM, bounded wait, then SIGKILL every still-running proc."""
    import signal
    import subprocess
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGTERM)
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def _launch_collective(nproc, script, script_args, coordinator=None,
                       nnodes=1, node_rank=0):
    """Reference: fleet/launch.py collective mode (launch.py:396 spawns
    nproc trainers with PADDLE_TRAINER_ID/ENDPOINTS). Multi-controller
    analogue: N processes per node jax.distributed.initialize against a
    coordinator (loopback when single-node); each sees the global device
    set (tested end-to-end in tests/test_dist_multiproc.py). A crashed
    rank terminates the whole job — surviving ranks would deadlock in
    their next collective waiting for it."""
    import subprocess
    import time
    if coordinator is None:
        coordinator = f"127.0.0.1:{_free_port()}"
    base = dict(os.environ)
    base["PADDLE_COORDINATOR"] = coordinator
    base["PADDLE_TRAINERS_NUM"] = str(nnodes * nproc)
    procs = []
    for i in range(nproc):
        env = dict(base, PADDLE_TRAINER_ID=str(node_rank * nproc + i))
        procs.append(subprocess.Popen(
            [sys.executable, script] + script_args, env=env))
    rc = 0
    try:
        while True:
            codes = [p.poll() for p in procs]
            failed = [c for c in codes if c not in (None, 0)]
            if failed:
                rc = failed[0]
                break
            if all(c == 0 for c in codes):
                break
            time.sleep(0.2)
    finally:
        _reap(procs)
    return rc


def launch():
    """python -m paddle_tpu.distributed.launch_mod
    [--coordinator host:port] [--nnodes N] [--node_rank R]
    [--nproc_per_node N]
    [--server_num N --worker_num M]  script.py args...

    With --server_num/--worker_num, spawns a local parameter-server
    cluster (reference: fleet/launch.py PS mode). With
    --nproc_per_node N (N>1), spawns a local N-process multi-controller
    collective run over a loopback coordinator."""
    argv = sys.argv[1:]
    coordinator = None
    nnodes = 1
    node_rank = 0
    server_num = 0
    worker_num = 0
    nproc_per_node = 1
    script_idx = 0
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--coordinator":
            coordinator = argv[i + 1]
            i += 2
        elif a == "--nproc_per_node":
            nproc_per_node = int(argv[i + 1])
            i += 2
        elif a == "--nnodes":
            nnodes = int(argv[i + 1])
            i += 2
        elif a == "--node_rank":
            node_rank = int(argv[i + 1])
            i += 2
        elif a == "--server_num":
            server_num = int(argv[i + 1])
            i += 2
        elif a == "--worker_num":
            worker_num = int(argv[i + 1])
            i += 2
        else:
            script_idx = i
            break
    script = argv[script_idx]
    script_args = argv[script_idx + 1:]
    if server_num > 0 and nproc_per_node > 1:
        sys.exit("--server_num (PS mode) and --nproc_per_node "
                 "(collective mode) are mutually exclusive")
    if server_num > 0 and (nnodes > 1 or coordinator):
        sys.exit("--nnodes/--coordinator do not apply to PS mode "
                 "(--server_num)")
    if nnodes > 1 and coordinator is None:
        sys.exit("--nnodes > 1 needs --coordinator host:port (a "
                 "per-node loopback coordinator cannot form one job)")
    if server_num > 0:
        sys.exit(_launch_ps_cluster(server_num, max(worker_num, 1),
                                    script, script_args))
    if nproc_per_node > 1:
        sys.exit(_launch_collective(nproc_per_node, script, script_args,
                                    coordinator=coordinator,
                                    nnodes=nnodes, node_rank=node_rank))
    if coordinator and nnodes > 1:
        os.environ["PADDLE_COORDINATOR"] = coordinator
        os.environ["PADDLE_TRAINERS_NUM"] = str(nnodes)
        os.environ["PADDLE_TRAINER_ID"] = str(node_rank)
    sys.argv = argv[script_idx:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    launch()
