"""Launcher.

Reference parity: python/paddle/distributed/fleet/launch.py:396 (process
launcher setting PADDLE_TRAINER_ID/ENDPOINTS per proc) and
python/paddle/distributed/spawn.py.

TPU-native: one controller process drives all local chips, so there is
nothing to spawn per device on a single host — `spawn(fn)` simply runs fn
(nprocs>1 on one host would fight over the TPU). Multi-host launch sets
the jax.distributed coordination env (PADDLE_COORDINATOR) per host; this
module can be used as `python -m paddle_tpu.distributed.launch_mod script.py`
on each host with PADDLE_TRAINER_ID set by the scheduler.
"""
import os
import runpy
import sys


def spawn(func, args=(), nprocs=1, join=True, daemon=False, **options):
    if nprocs not in (1, -1):
        raise RuntimeError(
            "paddle_tpu uses single-controller SPMD: one process drives all "
            "chips. Express device parallelism with fleet hybrid_configs / "
            "Mesh instead of spawning per-device processes.")
    return func(*args)


def launch():
    """python -m paddle_tpu.distributed.launch_mod [--coordinator host:port]
    [--nnodes N] [--node_rank R] script.py args..."""
    argv = sys.argv[1:]
    coordinator = None
    nnodes = 1
    node_rank = 0
    script_idx = 0
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--coordinator":
            coordinator = argv[i + 1]
            i += 2
        elif a == "--nnodes":
            nnodes = int(argv[i + 1])
            i += 2
        elif a == "--node_rank":
            node_rank = int(argv[i + 1])
            i += 2
        else:
            script_idx = i
            break
    if coordinator and nnodes > 1:
        os.environ["PADDLE_COORDINATOR"] = coordinator
        os.environ["PADDLE_TRAINERS_NUM"] = str(nnodes)
        os.environ["PADDLE_TRAINER_ID"] = str(node_rank)
    script = argv[script_idx]
    sys.argv = argv[script_idx:]
    runpy.run_path(script, run_name="__main__")


if __name__ == "__main__":
    launch()
