"""paddle.onnx — REAL ONNX graph export (reference:
python/paddle/onnx/export.py:21, which delegates to paddle2onnx over the
traced ProgramDesc).

TPU-native pipeline: the layer's forward is functionalized exactly like
jit.save's StableHLO path, but instead of serializing the XLA program,
the closed JAXPR is CONVERTED to an ONNX graph — jax primitives map to
ONNX ops, parameters become initializers, and every equation not
reachable from the graph inputs is constant-folded at export time
(parameter values are known, so only genuinely input-dependent
computation needs an op mapping). Protobuf bindings are generated from
the bundled official schema subset (paddle_tpu/onnx_proto/ — the onnx
pypi package is not in this image), so the output is a standard
`.onnx` file.

Supported primitive subset (export raises naming the primitive
otherwise): elementwise math/compares, MatMul-able dot_general,
conv_general_dilated (NCHW), reduce_window max/sum pooling, reductions,
reshape/transpose/broadcast/concat/slice/pad, embedding-style gather,
select_n, casts. Export traces on the host backend, so hardware-only
kernel paths (Pallas flash attention, fused CE) trace through their
reference compositions — which is what an interchange format wants.
"""
import os

import numpy as np

_OPSET = 13
_IR_VERSION = 8

_DTYPE_TO_ONNX = {
    "float32": 1, "uint8": 2, "int8": 3, "uint16": 4, "int16": 5,
    "int32": 6, "int64": 7, "bool": 9, "float16": 10, "float64": 11,
    "uint32": 12, "uint64": 13, "bfloat16": 16,
}

_CALL_PRIMS = {"jit", "pjit", "closed_call", "custom_jvp_call",
               "custom_vjp_call", "custom_jvp_call_jaxpr",
               "custom_vjp_call_jaxpr", "remat2", "checkpoint"}


def _pb():
    from .onnx_proto import onnx_pb2
    return onnx_pb2


def _jcore():
    try:
        import jax.extend.core as jec
        jec.Literal  # noqa: B018
        return jec
    except (ImportError, AttributeError):
        import jax
        return jax.core


def _onnx_dtype(np_dtype):
    code = _DTYPE_TO_ONNX.get(str(np.dtype(np_dtype)))
    if code is None:
        raise NotImplementedError(f"onnx export: dtype {np_dtype}")
    return code


class _Graph:
    """Builder state: nodes, initializers, fresh names."""

    def __init__(self):
        self.pb = _pb()
        self.nodes = []
        self.initializers = {}
        self._n = 0

    def fresh(self, hint="t"):
        self._n += 1
        return f"{hint}_{self._n}"

    def tensor_proto(self, name, arr):
        arr = np.asarray(arr)
        t = self.pb.TensorProto()
        t.name = name
        t.dims.extend(arr.shape)
        t.data_type = _onnx_dtype(arr.dtype)
        t.raw_data = np.ascontiguousarray(arr).tobytes()
        return t

    def add_init(self, arr, hint="const"):
        name = self.fresh(hint)
        self.initializers[name] = self.tensor_proto(name, arr)
        return name

    def node(self, op_type, inputs, n_out=1, name_hint=None, **attrs):
        n = self.pb.NodeProto()
        n.op_type = op_type
        n.input.extend(inputs)
        outs = [self.fresh(name_hint or op_type.lower())
                for _ in range(n_out)]
        n.output.extend(outs)
        n.name = outs[0]
        for k, v in attrs.items():
            a = n.attribute.add()
            a.name = k
            if isinstance(v, (bool, int, np.integer)):
                a.type = self.pb.AttributeProto.INT
                a.i = int(v)
            elif isinstance(v, float):
                a.type = self.pb.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, str):
                a.type = self.pb.AttributeProto.STRING
                a.s = v.encode()
            elif isinstance(v, (list, tuple)):
                if all(isinstance(x, (int, np.integer)) for x in v):
                    a.type = self.pb.AttributeProto.INTS
                    a.ints.extend(int(x) for x in v)
                else:
                    a.type = self.pb.AttributeProto.FLOATS
                    a.floats.extend(float(x) for x in v)
            else:
                raise TypeError(f"attr {k}={v!r}")
        self.nodes.append(n)
        return outs[0] if n_out == 1 else outs


class _Env:
    """jaxpr Var -> graph name and/or concrete value. A var with only a
    value is a foldable constant, materialized as an initializer on
    first graph use; a var with a name is a live graph edge."""

    def __init__(self, g):
        self.g = g
        self.names = {}
        self.values = {}

    def set_name(self, var, name):
        self.names[id(var)] = name

    def set_value(self, var, val):
        self.values[id(var)] = val

    def value(self, atom):
        if isinstance(atom, _jcore().Literal):
            return np.asarray(atom.val)
        return self.values.get(id(atom))

    def known(self, atom):
        return isinstance(atom, _jcore().Literal) \
            or id(atom) in self.values

    def name(self, atom, hint="const"):
        if isinstance(atom, _jcore().Literal):
            return self.g.add_init(np.asarray(atom.val), hint)
        nid = id(atom)
        if nid in self.names:
            return self.names[nid]
        if nid in self.values:
            name = self.g.add_init(np.asarray(self.values[nid]), hint)
            self.names[nid] = name
            return name
        raise KeyError(f"unbound jaxpr atom {atom}")


def _subjaxpr(eqn):
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is not None:
            if not hasattr(sub, "consts"):      # raw Jaxpr
                sub = _jcore().ClosedJaxpr(sub, ())
            return sub
    raise NotImplementedError(
        f"onnx export: call primitive {eqn.primitive.name} without an "
        "inlineable jaxpr")


def _eval_prim(eqn, invals):
    """Constant-fold one (non-call) equation on the host — call
    primitives are inlined by walk() before folding is attempted."""
    out = eqn.primitive.bind(*invals, **eqn.params)
    return out if eqn.primitive.multiple_results else [out]


# ---- per-primitive emitters ------------------------------------------------

_SIMPLE = {
    "add": "Add", "sub": "Sub", "mul": "Mul", "div": "Div",
    "max": "Max", "min": "Min", "pow": "Pow", "neg": "Neg",
    "exp": "Exp", "log": "Log", "tanh": "Tanh", "logistic": "Sigmoid",
    "sqrt": "Sqrt", "abs": "Abs", "sign": "Sign", "floor": "Floor",
    "ceil": "Ceil", "erf": "Erf", "and": "And", "or": "Or",
    "not": "Not",
}
_COMPARE = {"eq": "Equal", "lt": "Less", "le": "LessOrEqual",
            "gt": "Greater", "ge": "GreaterOrEqual"}


def _emit(g, env, eqn):
    """Emit ONNX node(s) for one input-dependent equation; returns the
    list of output names."""
    prim = eqn.primitive.name
    ins = eqn.invars
    p = eqn.params

    def nm(i, hint="in"):
        return env.name(ins[i], hint)

    if prim in _SIMPLE:
        return [g.node(_SIMPLE[prim], [nm(i) for i in range(len(ins))])]
    if prim in _COMPARE:
        return [g.node(_COMPARE[prim], [nm(0), nm(1)])]
    # synthesized scalar constants take the INPUT's dtype: a float32
    # literal next to a float64/float16 operand would fail ONNX's
    # same-dtype rule for binary ops
    def scalar(v, i=0):
        return g.add_init(np.asarray(v, ins[i].aval.dtype), "c")

    if prim == "integer_pow":
        return [g.node("Pow", [nm(0), scalar(float(p["y"]))])]
    if prim == "square":
        x = nm(0)
        return [g.node("Mul", [x, x])]
    if prim == "erfc":
        e = g.node("Erf", [nm(0)])
        return [g.node("Sub", [scalar(1.0), e])]
    if prim == "rsqrt":
        s = g.node("Sqrt", [nm(0)])
        return [g.node("Div", [scalar(1.0), s])]
    if prim in ("stop_gradient", "copy"):
        return [g.node("Identity", [nm(0)])]
    if prim == "convert_element_type":
        return [g.node("Cast", [nm(0)],
                       to=_onnx_dtype(np.dtype(p["new_dtype"])))]
    if prim == "select_n":
        if len(ins) != 3:
            raise NotImplementedError(
                f"onnx export: select_n with {len(ins) - 1} cases")
        # select_n(pred, case_false, case_true) -> Where(pred, T, F)
        return [g.node("Where", [nm(0), nm(2), nm(1)])]
    if prim == "transpose":
        return [g.node("Transpose", [nm(0)],
                       perm=list(p["permutation"]))]
    if prim in ("reshape", "squeeze", "expand_dims"):
        # NB: squeeze/expand_dims use "dimensions" for their AXES; only
        # lax.reshape's dimensions= means permute-before-reshape
        if prim == "reshape" and p.get("dimensions") is not None:
            raise NotImplementedError(
                "onnx export: lax.reshape with dimensions= (permute-"
                "before-reshape)")
        shape = g.add_init(
            np.asarray(eqn.outvars[0].aval.shape, np.int64), "shape")
        return [g.node("Reshape", [nm(0), shape])]
    if prim == "broadcast_in_dim":
        shape = p["shape"]
        bdims = p["broadcast_dimensions"]
        inter = [1] * len(shape)
        for src, dst in enumerate(bdims):
            inter[dst] = ins[0].aval.shape[src]
        rs = g.add_init(np.asarray(inter, np.int64), "shape")
        mid = g.node("Reshape", [nm(0), rs])
        tgt = g.add_init(np.asarray(shape, np.int64), "shape")
        return [g.node("Expand", [mid, tgt])]
    if prim == "split":
        sizes = list(p["sizes"])
        sp = g.add_init(np.asarray(sizes, np.int64), "split")
        outs = g.node("Split", [nm(0), sp], n_out=len(sizes),
                      axis=int(p["axis"]))
        return outs if isinstance(outs, list) else [outs]
    if prim == "concatenate":
        return [g.node("Concat", [nm(i) for i in range(len(ins))],
                       axis=int(p["dimension"]))]
    if prim == "slice":
        strides = (list(p["strides"]) if p.get("strides") is not None
                   else [1] * len(p["start_indices"]))
        mk = lambda v, h: g.add_init(np.asarray(v, np.int64), h)  # noqa: E731
        return [g.node("Slice", [
            nm(0), mk(p["start_indices"], "starts"),
            mk(p["limit_indices"], "ends"),
            mk(range(len(strides)), "axes"), mk(strides, "steps")])]
    if prim == "pad":
        lo, hi, interior = zip(*p["padding_config"])
        if any(i != 0 for i in interior):
            raise NotImplementedError("onnx export: interior padding")
        if any(v < 0 for v in list(lo) + list(hi)):
            raise NotImplementedError("onnx export: negative padding")
        pads = g.add_init(np.asarray(list(lo) + list(hi), np.int64),
                          "pads")
        return [g.node("Pad", [nm(0), pads, nm(1, "padval")],
                       mode="constant")]
    if prim == "reduce_sum":
        axes = g.add_init(np.asarray(p["axes"], np.int64), "axes")
        return [g.node("ReduceSum", [nm(0), axes], keepdims=0)]
    if prim in ("reduce_max", "reduce_min", "reduce_prod"):
        op = {"reduce_max": "ReduceMax", "reduce_min": "ReduceMin",
              "reduce_prod": "ReduceProd"}[prim]
        return [g.node(op, [nm(0)], axes=list(p["axes"]), keepdims=0)]
    if prim == "dot_general":
        return [_emit_dot(g, env, eqn)]
    if prim == "conv_general_dilated":
        return [_emit_conv(g, env, eqn)]
    if prim in ("reduce_window_max", "reduce_window_sum"):
        return [_emit_pool(g, env, eqn)]
    if prim == "gather":
        return [_emit_gather(g, env, eqn)]
    raise NotImplementedError(
        f"onnx export: jax primitive {prim!r} has no ONNX mapping in "
        "this build (supported: elementwise/matmul/conv/pool/reduce/"
        "shape ops). Keep the exported forward to inference ops, or "
        "use jit.save (StableHLO) for full-fidelity interchange.")


def _emit_dot(g, env, eqn):
    """dot_general -> ONNX. Fast path: the cases whose free-dim layout
    already agrees with MatMul's numpy batching emit one MatMul (plus a
    contraction-axis Transpose when needed). Everything else — >=2 free
    dims beside a batched side, multi-dim contraction, non-leading or
    vector-side batch dims — canonicalizes: Transpose each side to
    [batch..., free..., contract...], Reshape to 3D-style
    [B..., prod(free), prod(K)] / [B..., prod(K), prod(free)], MatMul,
    Reshape to dot_general's exact output shape (batch, lhs free, rhs
    free — the layout the jaxpr's out aval records)."""
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    a, b = eqn.invars
    ar, br = len(a.aval.shape), len(b.aval.shape)
    lhs_free = ar - len(lb) - len(lc)
    rhs_free = br - len(rb) - len(rc)
    fast = (len(lc) == 1 and len(rc) == 1
            and tuple(lb) == tuple(range(len(lb)))
            and tuple(rb) == tuple(range(len(rb)))
            and not (lb and (ar < len(lb) + 2 or br < len(rb) + 2))
            and ((lhs_free <= 1 and rhs_free <= 1)
                 or (br == 2 and not rb)))
    an = env.name(a, "a")
    bn = env.name(b, "b")
    if fast:
        lc0, rc0 = lc[0], rc[0]
        if lc0 != ar - 1:  # lhs contraction must be the last axis
            perm = [i for i in range(ar) if i != lc0] + [lc0]
            an = g.node("Transpose", [an], perm=perm)
        want = len(rb)     # rhs contraction right after the batch dims
        if rc0 != want:
            perm = list(range(want)) + [rc0] + \
                [i for i in range(br) if i >= want and i != rc0]
            bn = g.node("Transpose", [bn], perm=perm)
        return g.node("MatMul", [an, bn])

    ash, bsh = a.aval.shape, b.aval.shape
    out_aval_shape = eqn.outvars[0].aval.shape
    if not all(isinstance(d, (int, np.integer))
               for d in (*ash, *bsh, *out_aval_shape)):
        # shape-polymorphic tracing (jax.export symbolic dims) reaches
        # here with _DimExpr dims; the int() bakes below would raise a
        # bare TypeError — fail with the exporter's standard signal
        raise NotImplementedError(
            "onnx export: dynamic dims in dot_general canonicalization "
            "(the general path bakes static Reshape targets; export "
            "with concrete shapes)")
    fl = [i for i in range(ar) if i not in lb and i not in lc]
    fr = [i for i in range(br) if i not in rb and i not in rc]
    perm_l = list(lb) + fl + list(lc)
    perm_r = list(rb) + list(rc) + fr
    if perm_l != list(range(ar)):
        an = g.node("Transpose", [an], perm=perm_l)
    if perm_r != list(range(br)):
        bn = g.node("Transpose", [bn], perm=perm_r)
    bshape = [int(ash[i]) for i in lb]
    k = int(np.prod([ash[i] for i in lc], dtype=np.int64))
    m = int(np.prod([ash[i] for i in fl], dtype=np.int64))
    n = int(np.prod([bsh[i] for i in fr], dtype=np.int64))
    an = g.node("Reshape", [an, g.add_init(
        np.asarray(bshape + [m, k], np.int64), "shape")])
    bn = g.node("Reshape", [bn, g.add_init(
        np.asarray(bshape + [k, n], np.int64), "shape")])
    mm = g.node("MatMul", [an, bn])
    out_shape = [int(d) for d in out_aval_shape]
    return g.node("Reshape", [mm, g.add_init(
        np.asarray(out_shape, np.int64), "shape")])


def _emit_conv(g, env, eqn):
    p = eqn.params
    dn = p["dimension_numbers"]
    if tuple(dn.lhs_spec) != tuple(range(len(dn.lhs_spec))) or \
            tuple(dn.rhs_spec) != tuple(range(len(dn.rhs_spec))) or \
            tuple(dn.out_spec) != tuple(range(len(dn.out_spec))):
        raise NotImplementedError(
            "onnx export: conv layouts other than NCHW/OIHW")
    if any(d != 1 for d in p["lhs_dilation"]):
        raise NotImplementedError("onnx export: transposed conv")
    if p.get("batch_group_count", 1) != 1:
        raise NotImplementedError("onnx export: batch_group_count > 1")
    return g.node(
        "Conv", [env.name(eqn.invars[0], "x"),
                 env.name(eqn.invars[1], "w")],
        strides=list(p["window_strides"]),
        pads=[pp[0] for pp in p["padding"]]
        + [pp[1] for pp in p["padding"]],
        dilations=list(p["rhs_dilation"]),
        group=int(p["feature_group_count"]))


def _emit_pool(g, env, eqn):
    p = eqn.params
    wd = list(p["window_dimensions"])
    allstr = list(p["window_strides"])
    allpad = list(p["padding"])
    if (len(wd) < 3 or wd[0] != 1 or wd[1] != 1
            or allstr[0] != 1 or allstr[1] != 1
            or tuple(allpad[0]) != (0, 0) or tuple(allpad[1]) != (0, 0)
            or any(d != 1 for d in p.get("window_dilation", ()) or ())
            or any(d != 1 for d in p.get("base_dilation", ()) or ())):
        raise NotImplementedError(
            "onnx export: reduce_window with non-spatial windowing, "
            "batch/channel strides or padding, or dilation")
    kernel = wd[2:]
    strides = allstr[2:]
    pad = allpad[2:]
    pads = [pp[0] for pp in pad] + [pp[1] for pp in pad]
    x = env.name(eqn.invars[0], "x")
    if eqn.primitive.name == "reduce_window_max":
        return g.node("MaxPool", [x], kernel_shape=kernel,
                      strides=strides, pads=pads)
    # sum-window = AveragePool(count_include_pad) * window_size
    ap = g.node("AveragePool", [x], kernel_shape=kernel,
                strides=strides, pads=pads, count_include_pad=1)
    k = g.add_init(np.asarray(float(np.prod(kernel)), np.float32),
                   "winsize")
    return g.node("Mul", [ap, k])


def _emit_gather(g, env, eqn):
    """lax.gather in its point-lookup form (slice size 1 on every
    indexed dim, full on the rest): embedding row lookups, jnp.take,
    and the strided-window indexing jnp lowers pooling slices to. Maps
    to Gather (single indexed leading dim) or Transpose+GatherND+
    Transpose in general."""
    p = eqn.params
    dn = p["dimension_numbers"]
    operand, indices = eqn.invars
    oshape = operand.aval.shape
    ishape = indices.aval.shape
    slice_sizes = tuple(p["slice_sizes"])
    idx_dims = tuple(dn.start_index_map)
    if (tuple(dn.collapsed_slice_dims) != idx_dims
            or tuple(getattr(dn, "operand_batching_dims", ())) != ()
            or any(slice_sizes[d] != 1 for d in idx_dims)
            or (not ishape or ishape[-1] != len(idx_dims))):
        raise NotImplementedError(
            "onnx export: general lax.gather (only point-lookup "
            "gathers are mapped)")
    keep_dims = [d for d in range(len(oshape)) if d not in idx_dims]
    if any(slice_sizes[d] != oshape[d] for d in keep_dims):
        raise NotImplementedError(
            "onnx export: lax.gather with partial non-indexed slices")

    op_name = env.name(operand, "table")
    idx_name = env.name(indices, "ids")
    n_batch = len(ishape) - 1

    if idx_dims == (0,):  # embedding form: plain Gather
        shape = g.add_init(np.asarray(ishape[:-1], np.int64), "shape")
        flat_idx = g.node("Reshape", [idx_name, shape])
        gathered = g.node("Gather", [op_name, flat_idx], axis=0)
    else:
        # data -> [indexed dims..., keep dims...] so GatherND's implicit
        # leading-dim indexing lines up
        perm_in = list(idx_dims) + keep_dims
        tr = g.node("Transpose", [op_name], perm=perm_in)
        gathered = g.node("GatherND", [tr, idx_name])
    # gathered: [batch..., keep...]; jax places keep dims at the
    # offset_dims OUTPUT positions and batch dims at the rest, in order
    out_rank = n_batch + len(keep_dims)
    offset = list(dn.offset_dims)
    batch_pos = [i for i in range(out_rank) if i not in offset]
    perm_out = [0] * out_rank
    for k, pos in enumerate(batch_pos):
        perm_out[pos] = k
    for k, pos in enumerate(offset):
        perm_out[pos] = n_batch + k
    if perm_out != list(range(out_rank)):
        gathered = g.node("Transpose", [gathered], perm=perm_out)
    return gathered


# ---- driver ----------------------------------------------------------------

def _convert(closed, param_names, param_values, input_names,
             graph_name):
    pb = _pb()
    g = _Graph()
    env = _Env(g)
    jaxpr = closed.jaxpr

    for var, val in zip(jaxpr.constvars, closed.consts):
        env.set_value(var, np.asarray(val))

    def walk(jx):
        for eqn in jx.eqns:
            if eqn.primitive.name in _CALL_PRIMS:
                sub = _subjaxpr(eqn)
                for cv, cval in zip(sub.jaxpr.constvars, sub.consts):
                    env.set_value(cv, np.asarray(cval))
                for v, a in zip(sub.jaxpr.invars, eqn.invars):
                    if env.known(a):
                        env.set_value(v, env.value(a))
                    if id(a) in env.names:
                        env.set_name(v, env.names[id(a)])
                walk(sub.jaxpr)
                for out, sub_out in zip(eqn.outvars, sub.jaxpr.outvars):
                    if env.known(sub_out):
                        env.set_value(out, env.value(sub_out))
                    if isinstance(sub_out, _jcore().Literal) \
                            or id(sub_out) in env.names:
                        env.set_name(out, env.name(sub_out))
                continue
            if all(env.known(a) for a in eqn.invars):
                try:
                    outs = _eval_prim(eqn,
                                      [env.value(a) for a in eqn.invars])
                except Exception:  # noqa: BLE001 — emit instead
                    outs = None
                if outs is not None:
                    for var, val in zip(eqn.outvars, outs):
                        env.set_value(var, np.asarray(val))
                    continue
            outs = _emit(g, env, eqn)
            for var, name in zip(eqn.outvars, outs):
                env.set_name(var, name)

    invars = jaxpr.invars
    n_params = len(param_names)
    pvars, xvars = invars[:n_params], invars[n_params:]
    # parameters get BOTH a stable name and their value: equations
    # touching only parameters (weight casts, shape constants) fold at
    # export time; live references resolve to named initializers below
    for v, n, val in zip(pvars, param_names, param_values):
        env.set_name(v, n)
        env.set_value(v, np.asarray(val))
    for v, n in zip(xvars, input_names):
        env.set_name(v, n)
    walk(jaxpr)

    model = pb.ModelProto()
    model.ir_version = _IR_VERSION
    model.producer_name = "paddle_tpu"
    opset = model.opset_import.add()
    opset.domain = ""
    opset.version = _OPSET
    graph = model.graph
    graph.name = graph_name

    def vinfo(name, aval):
        vi = pb.ValueInfoProto()
        vi.name = name
        tt = vi.type.tensor_type
        tt.elem_type = _onnx_dtype(np.dtype(aval.dtype))
        for s in aval.shape:
            tt.shape.dim.add().dim_value = int(s)
        return vi

    # resolve outputs BEFORE copying nodes/initializers: resolving a
    # fully-folded output can CREATE an initializer, and the Identity
    # wrapper below appends a node — both must land in the graph
    for v, n in zip(xvars, input_names):
        graph.input.add().CopyFrom(vinfo(n, v.aval))
    for out in jaxpr.outvars:
        name = env.name(out, "output")
        if name in g.initializers or name in set(input_names):
            # ONNX requires graph outputs to be produced by nodes: a
            # fully constant-folded output (resolves to an initializer)
            # or an input passthrough must go through an Identity or
            # strict checkers/runtimes reject the model
            name = g.node("Identity", [name])
        graph.output.add().CopyFrom(vinfo(name, out.aval))
    graph.node.extend(g.nodes)
    for t in g.initializers.values():
        graph.initializer.add().CopyFrom(t)
    return model, g


def export(layer, path, input_spec=None, opset_version=_OPSET,
           **configs):
    """Write `path + '.onnx'`; returns the .onnx path. Reference:
    paddle.onnx.export (export.py:21).

    opset_version: 13-17 honored as declared (the emitted op forms —
    ReduceSum axes-as-input, Slice inputs — need >= 13 and predate the
    18/19 reduce changes); anything lower is raised to 13 with a
    warning rather than emitting ops the requested opset can't hold."""
    import warnings

    import jax
    import jax.numpy as jnp

    from .core import trace as trace_mod
    from .core.tensor import Tensor
    from .core.dtype import to_jax_dtype
    from .static.input_spec import InputSpec

    if input_spec is None:
        raise ValueError("onnx.export requires input_spec")
    examples = []
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec.value)
        elif isinstance(spec, InputSpec):
            shape = tuple(1 if (s is None or s < 0) else int(s)
                          for s in spec.shape)
            examples.append(jnp.zeros(shape, to_jax_dtype(spec.dtype)))
        else:
            examples.append(jnp.asarray(spec))

    layer.eval()
    params = layer.state_dict()
    names = list(params.keys())
    values = [params[n].value for n in names]

    def pure_fn(param_values, *inputs):
        ctx = trace_mod.TraceContext("jit")
        with trace_mod.trace_guard(ctx):
            for n, v in zip(names, param_values):
                ctx.bind(params[n], v)
            in_tensors = [Tensor(x) for x in inputs]
            for t in in_tensors:
                ctx.register_created(t)
            out = layer(*in_tensors)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o.value for o in outs]

    opset = int(opset_version)
    if opset < _OPSET:
        warnings.warn(
            f"onnx export: opset_version={opset_version} is below the "
            f"minimum this converter's op forms need; emitting opset "
            f"{_OPSET}")
        opset = _OPSET
    elif opset > 17:
        warnings.warn(
            f"onnx export: opset_version={opset_version} is beyond the "
            "validated range (13-17: ReduceMax/Min axes moved to "
            "inputs in 18); emitting opset 17")
        opset = 17

    closed = jax.make_jaxpr(pure_fn)(values, *examples)
    input_names = [f"x{i}" for i in range(len(examples))]
    model, g = _convert(closed, names, values, input_names,
                        graph_name=type(layer).__name__)
    model.opset_import[0].version = opset

    # attach the values of parameters the graph references by name
    have = {t.name for t in model.graph.initializer}
    used = set()
    for n in model.graph.node:
        used.update(n.input)
    for n, v in zip(names, values):
        if n in used and n not in have:
            model.graph.initializer.add().CopyFrom(
                g.tensor_proto(n, np.asarray(v)))

    out_path = path if path.endswith(".onnx") else path + ".onnx"
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "wb") as f:
        f.write(model.SerializeToString())
    return out_path
