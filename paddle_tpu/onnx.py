"""paddle.onnx equivalent (reference: python/paddle/onnx/ — export
delegates to the external paddle2onnx package).

ONNX graph emission is not implemented; the TPU-native interchange format
is the StableHLO/jit program (what the inference Predictor and jit.load
consume), and `export` always produces that artifact. A warning makes the
format explicit so downstream ONNX tooling fails at export time, not
later on a missing .onnx file.
"""
import warnings


def export(layer, path, input_spec=None, opset_version=9, **configs):
    from . import jit
    jit.save(layer, path, input_spec=input_spec)
    warnings.warn(
        "paddle_tpu.onnx.export emits a StableHLO/jit program at "
        f"{path} (loadable by paddle_tpu.jit.load / inference Predictor); "
        ".onnx graph emission is not supported in this build")
    return path
