"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy CHW float implementations of the common set."""
import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        return (img - self.mean) / self.std


class ToTensor:
    def __init__(self, data_format="CHW"):
        pass

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if not chw and arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        out = jax.image.resize(jnp.asarray(arr),
                               (arr.shape[0],) + self.size, method="linear")
        return np.asarray(out)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[..., ::-1])
        return np.asarray(img)


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            arr = np.pad(arr, ((0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)), mode="constant")
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return arr[..., i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)
