"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy CHW float implementations of the common set."""
import numpy as np


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self.std = np.asarray(std, np.float32).reshape(-1, 1, 1)

    def __call__(self, img):
        img = np.asarray(img, np.float32)
        return (img - self.mean) / self.std


class ToTensor:
    def __init__(self, data_format="CHW"):
        pass

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[None]
        elif arr.ndim == 3 and arr.shape[-1] in (1, 3, 4):
            arr = arr.transpose(2, 0, 1)
        if arr.max() > 1.5:
            arr = arr / 255.0
        return arr


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        import jax
        import jax.numpy as jnp
        arr = np.asarray(img, np.float32)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3, 4)
        if not chw and arr.ndim == 3:
            arr = arr.transpose(2, 0, 1)
        out = jax.image.resize(jnp.asarray(arr),
                               (arr.shape[0],) + self.size, method="linear")
        return np.asarray(out)


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[..., ::-1])
        return np.asarray(img)


class RandomCrop:
    def __init__(self, size, padding=0):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            arr = np.pad(arr, ((0, 0), (self.padding, self.padding),
                               (self.padding, self.padding)), mode="constant")
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return arr[..., i:i + th, j:j + tw]


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else tuple(size)

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[-2:]
        th, tw = self.size
        i = (h - th) // 2
        j = (w - tw) // 2
        return arr[..., i:i + th, j:j + tw]


def to_tensor(img, data_format="CHW"):
    return ToTensor(data_format)(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    return Normalize(mean, std, data_format)(img)


def resize(img, size, interpolation="bilinear"):
    return Resize(size, interpolation)(img)


class BaseTransform:
    """Reference: transforms.py BaseTransform — subclass and implement
    _apply_image (and optionally _apply_* for other keys)."""

    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        if self.keys is None:
            return self._apply_image(inputs)
        inputs = list(inputs)
        for i, k in enumerate(self.keys):
            fn = getattr(self, f"_apply_{k}", None)
            if fn is not None:
                inputs[i] = fn(inputs[i])
        return tuple(inputs)

    def _apply_image(self, img):
        return img


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.ascontiguousarray(np.asarray(img)[..., ::-1, :])
        return np.asarray(img)


class Transpose:
    """HWC -> CHW by default (reference: Transpose(order))."""

    def __init__(self, order=(2, 0, 1)):
        self.order = tuple(order)

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3),
                 interpolation="bilinear"):
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        h, w = arr.shape[-2:]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]),
                                          np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target * ar)))
            ch = int(round(np.sqrt(target / ar)))
            if 0 < cw <= w and 0 < ch <= h:
                i = np.random.randint(0, h - ch + 1)
                j = np.random.randint(0, w - cw + 1)
                crop_arr = arr[..., i:i + ch, j:j + cw]
                return Resize(self.size)(crop_arr)
        return Resize(self.size)(CenterCrop(min(h, w))(arr))


def _rgb_to_gray(arr):
    # arr CHW with C==3
    r, g, b = arr[0], arr[1], arr[2]
    return 0.299 * r + 0.587 * g + 0.114 * b


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.n = num_output_channels

    def __call__(self, img):
        arr = np.asarray(img, np.float32)
        if arr.shape[0] == 1:
            gray = arr[0]
        else:
            gray = _rgb_to_gray(arr)
        return np.repeat(gray[None], self.n, axis=0)


class BrightnessTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        alpha = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, alpha)


class ContrastTransform:
    def __init__(self, value):
        if value < 0:
            raise ValueError("contrast value must be non-negative")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        alpha = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, alpha)


class SaturationTransform:
    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        alpha = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, alpha)


class HueTransform:
    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter:
    """Reference: ColorJitter — apply the four jitters in random order."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self.ts = [BrightnessTransform(brightness),
                   ContrastTransform(contrast),
                   SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self.ts))
        for i in order:
            img = self.ts[i](img)
        return img


class Pad:
    def __init__(self, padding, fill=0, padding_mode="constant"):
        self.padding = (padding,) * 4 if isinstance(padding, int) else \
            (tuple(padding) * 2 if len(padding) == 2 else tuple(padding))
        self.fill = fill
        self.mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        left, top, right, bottom = self.padding
        pad_width = ((0, 0), (top, bottom), (left, right))
        if self.mode == "constant":
            return np.pad(arr, pad_width, mode="constant",
                          constant_values=self.fill)
        return np.pad(arr, pad_width, mode=self.mode)


class RandomRotation:
    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-degrees, degrees)
        self.degrees = tuple(degrees)
        self.fill = fill

    def __call__(self, img):
        angle = np.random.uniform(*self.degrees)
        return rotate(img, angle, fill=self.fill)


def hflip(img):
    return np.ascontiguousarray(np.asarray(img)[..., ::-1])


def vflip(img):
    return np.ascontiguousarray(np.asarray(img)[..., ::-1, :])


def crop(img, top, left, height, width):
    return np.asarray(img)[..., top:top + height, left:left + width]


def center_crop(img, output_size):
    return CenterCrop(output_size)(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    return Pad(padding, fill, padding_mode)(img)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)(img)


def adjust_brightness(img, brightness_factor):
    return np.asarray(img, np.float32) * brightness_factor


def adjust_contrast(img, contrast_factor):
    arr = np.asarray(img, np.float32)
    mean = _rgb_to_gray(arr).mean() if arr.shape[0] == 3 else arr.mean()
    return arr * contrast_factor + mean * (1 - contrast_factor)


def adjust_saturation(img, saturation_factor):
    arr = np.asarray(img, np.float32)
    if arr.shape[0] != 3:
        return arr
    gray = _rgb_to_gray(arr)[None]
    return arr * saturation_factor + gray * (1 - saturation_factor)


def adjust_hue(img, hue_factor):
    arr = np.asarray(img, np.float32)
    if hue_factor == 0 or arr.shape[0] != 3:
        return arr
    shift = hue_factor * 2 * np.pi
    u, w_ = np.cos(shift), np.sin(shift)
    t_yiq = np.array([[0.299, 0.587, 0.114],
                      [0.596, -0.274, -0.321],
                      [0.211, -0.523, 0.311]], np.float32)
    t_rot = np.array([[1, 0, 0], [0, u, -w_], [0, w_, u]], np.float32)
    t_rgb = np.linalg.inv(t_yiq) @ t_rot @ t_yiq
    return (t_rgb @ arr.reshape(3, -1)).reshape(arr.shape)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate CHW image by `angle` degrees (nearest sampling)."""
    arr = np.asarray(img, np.float32)
    h, w = arr.shape[-2:]
    cy, cx = ((h - 1) / 2.0, (w - 1) / 2.0) if center is None else center
    rad = -np.deg2rad(angle)  # positive angle = counterclockwise (PIL)
    cos_a, sin_a = np.cos(rad), np.sin(rad)
    yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
    # inverse mapping: output pixel -> source pixel
    sx = cos_a * (xx - cx) + sin_a * (yy - cy) + cx
    sy = -sin_a * (xx - cx) + cos_a * (yy - cy) + cy
    sxi = np.round(sx).astype(np.int64)
    syi = np.round(sy).astype(np.int64)
    valid = (sxi >= 0) & (sxi < w) & (syi >= 0) & (syi < h)
    sxi = np.clip(sxi, 0, w - 1)
    syi = np.clip(syi, 0, h - 1)
    out = arr[..., syi, sxi]
    out = np.where(valid, out, fill)
    return out.astype(arr.dtype)
