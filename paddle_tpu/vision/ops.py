"""paddle.vision.ops equivalent: detection operators.

Reference parity: python/paddle/vision/ops.py (__all__: yolo_loss,
yolo_box, deform_conv2d, DeformConv2D, read_file, decode_jpeg) plus the
widely used detection kernels roi_align / nms from
paddle/fluid/operators/detection/ (yolo_box_op.h, yolov3_loss_op.h,
roi_align_op.h, deformable_conv_op.h).

TPU-native design: everything is dense, vectorized jnp — grid decode and
bilinear sampling map to gathers XLA fuses well; there is no per-box
scalar loop. Greedy NMS is O(n^2) mask iteration on host (it is an
inference post-process, sequential by definition).
"""
import numpy as np
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn import initializer as init_mod


def _sigmoid(v):
    return 1.0 / (1.0 + jnp.exp(-v))


@register_op("yolo_box")
def _yolo_box(x, img_size, *, anchors, class_num, conf_thresh,
              downsample_ratio, clip_bbox, scale_x_y):
    """Reference: detection/yolo_box_op.h GetYoloBox/CalcDetectionBox."""
    n, c, h, w = x.shape
    an_num = len(anchors) // 2
    bias = -0.5 * (scale_x_y - 1.0)
    input_h = downsample_ratio * h
    input_w = downsample_ratio * w

    # [N, an, 5+cls, H, W]
    pred = x.reshape(n, an_num, 5 + class_num, h, w).astype(jnp.float32)
    grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
    img_h = img_size[:, 0].astype(jnp.float32)[:, None, None, None]
    img_w = img_size[:, 1].astype(jnp.float32)[:, None, None, None]
    anc = jnp.asarray(anchors, jnp.float32).reshape(an_num, 2)
    anc_w = anc[:, 0][None, :, None, None]
    anc_h = anc[:, 1][None, :, None, None]

    cx = (grid_x + _sigmoid(pred[:, :, 0]) * scale_x_y + bias) * img_w / w
    cy = (grid_y + _sigmoid(pred[:, :, 1]) * scale_x_y + bias) * img_h / h
    bw = jnp.exp(pred[:, :, 2]) * anc_w * img_w / input_w
    bh = jnp.exp(pred[:, :, 3]) * anc_h * img_h / input_h
    conf = _sigmoid(pred[:, :, 4])
    keep = (conf >= conf_thresh).astype(jnp.float32)

    x1, y1 = cx - bw / 2, cy - bh / 2
    x2, y2 = cx + bw / 2, cy + bh / 2
    if clip_bbox:
        x1 = jnp.clip(x1, 0.0, None)
        y1 = jnp.clip(y1, 0.0, None)
        x2 = jnp.minimum(x2, img_w - 1.0)
        y2 = jnp.minimum(y2, img_h - 1.0)
    boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
    scores = conf[:, :, None] * _sigmoid(pred[:, :, 5:]) * keep[:, :, None]

    # [N, an*H*W, 4] / [N, an*H*W, cls]
    boxes = boxes.transpose(0, 1, 3, 4, 2).reshape(n, -1, 4)
    scores = scores.transpose(0, 1, 3, 4, 2).reshape(n, -1, class_num)
    return boxes, scores


def yolo_box(x, img_size, anchors, class_num, conf_thresh=0.01,
             downsample_ratio=32, clip_bbox=True, name=None,
             scale_x_y=1.0):
    return _yolo_box(x, img_size, anchors=tuple(anchors),
                     class_num=class_num, conf_thresh=conf_thresh,
                     downsample_ratio=downsample_ratio,
                     clip_bbox=clip_bbox, scale_x_y=scale_x_y)


def _bce(pred_logit, target):
    p = _sigmoid(pred_logit)
    p = jnp.clip(p, 1e-7, 1.0 - 1e-7)
    return -(target * jnp.log(p) + (1.0 - target) * jnp.log(1.0 - p))


def _wh_iou(w1, h1, w2, h2):
    inter = jnp.minimum(w1, w2) * jnp.minimum(h1, h2)
    return inter / (w1 * h1 + w2 * h2 - inter + 1e-9)


@register_op("yolov3_loss")
def _yolo_loss(x, gt_box, gt_label, gt_score, *, anchors, anchor_mask,
               class_num, ignore_thresh, downsample_ratio, use_label_smooth,
               scale_x_y):
    """Reference: detection/yolov3_loss_op.h — anchor-matched targets,
    BCE x/y + L1 w/h (weighted by 2-w*h), objectness with ignore_thresh,
    per-class BCE. gt_box: [N,B,4] normalized cx,cy,w,h; gt_label: [N,B];
    gt_score: [N,B] (mixup weight, ones by default)."""
    n, c, h, w = x.shape
    mask_num = len(anchor_mask)
    an_all = jnp.asarray(anchors, jnp.float32).reshape(-1, 2)
    input_size = downsample_ratio * h
    pred = x.reshape(n, mask_num, 5 + class_num, h, w).astype(jnp.float32)
    bsz = gt_box.shape[1]

    valid = (gt_box[:, :, 2] > 0).astype(jnp.float32)  # [N,B]

    # best anchor (over ALL anchors) per gt via w/h IoU — reference
    # matches in input-size pixel space
    gw = gt_box[:, :, 2] * input_size
    gh = gt_box[:, :, 3] * input_size
    ious = _wh_iou(gw[:, :, None], gh[:, :, None],
                   an_all[None, None, :, 0], an_all[None, None, :, 1])
    best_an = jnp.argmax(ious, axis=-1)  # [N,B]

    # map best anchor -> local head slot (or -1)
    mask_arr = jnp.asarray(anchor_mask, jnp.int32)
    local_slot = jnp.argmax(
        (best_an[:, :, None] == mask_arr[None, None, :]), axis=-1)
    in_head = jnp.any(best_an[:, :, None] == mask_arr[None, None, :],
                      axis=-1).astype(jnp.float32) * valid

    gi = jnp.clip((gt_box[:, :, 0] * w).astype(jnp.int32), 0, w - 1)
    gj = jnp.clip((gt_box[:, :, 1] * h).astype(jnp.int32), 0, h - 1)

    # targets
    tx = gt_box[:, :, 0] * w - gi.astype(jnp.float32)
    ty = gt_box[:, :, 1] * h - gj.astype(jnp.float32)
    # tw/th depend on the assigned anchor
    tw = jnp.log(jnp.clip(
        gw[:, :, None] / an_all[mask_arr][None, None, :, 0], 1e-9, None))
    th = jnp.log(jnp.clip(
        gh[:, :, None] / an_all[mask_arr][None, None, :, 1], 1e-9, None))
    tw = jnp.take_along_axis(tw, local_slot[:, :, None], -1)[:, :, 0]
    th = jnp.take_along_axis(th, local_slot[:, :, None], -1)[:, :, 0]
    box_scale = 2.0 - gt_box[:, :, 2] * gt_box[:, :, 3]

    # gather predictions at assigned (slot, gj, gi) per gt
    flat = pred.transpose(0, 1, 3, 4, 2).reshape(n, mask_num * h * w,
                                                 5 + class_num)
    gt_idx = local_slot * h * w + gj * w + gi  # [N,B]
    pg = jnp.take_along_axis(
        flat, gt_idx[:, :, None].astype(jnp.int32), axis=1)  # [N,B,5+cls]

    wsc = in_head * gt_score * box_scale
    loss_xy = (_bce(pg[:, :, 0], tx) + _bce(pg[:, :, 1], ty)) * wsc
    loss_wh = (jnp.abs(pg[:, :, 2] - tw) + jnp.abs(pg[:, :, 3] - th)) * wsc

    # class loss
    smooth_pos = 1.0 - 1.0 / class_num if use_label_smooth else 1.0
    smooth_neg = 1.0 / class_num if use_label_smooth else 0.0
    onehot = (jnp.arange(class_num)[None, None, :]
              == gt_label[:, :, None]).astype(jnp.float32)
    tcls = onehot * smooth_pos + (1.0 - onehot) * smooth_neg
    loss_cls = (_bce(pg[:, :, 5:], tcls).sum(-1) * in_head * gt_score)

    # objectness: positive at assigned cells; ignore preds whose IoU with
    # any gt exceeds ignore_thresh
    obj_logit = pred[:, :, 4]  # [N,mask,h,w]
    grid_x = (jnp.arange(w, dtype=jnp.float32) + 0.5)[None, None, None, :]
    grid_y = (jnp.arange(h, dtype=jnp.float32) + 0.5)[None, None, :, None]
    px = (grid_x - 0.5 + _sigmoid(pred[:, :, 0])) / w
    py = (grid_y - 0.5 + _sigmoid(pred[:, :, 1])) / h
    pw = jnp.exp(pred[:, :, 2]) * an_all[mask_arr][None, :, 0, None, None] \
        / input_size
    ph = jnp.exp(pred[:, :, 3]) * an_all[mask_arr][None, :, 1, None, None] \
        / input_size
    # IoU of every pred box with every gt box [N, mask, h, w, B]
    px1, py1 = px - pw / 2, py - ph / 2
    px2, py2 = px + pw / 2, py + ph / 2
    gx1 = (gt_box[:, :, 0] - gt_box[:, :, 2] / 2)[:, None, None, None, :]
    gy1 = (gt_box[:, :, 1] - gt_box[:, :, 3] / 2)[:, None, None, None, :]
    gx2 = (gt_box[:, :, 0] + gt_box[:, :, 2] / 2)[:, None, None, None, :]
    gy2 = (gt_box[:, :, 1] + gt_box[:, :, 3] / 2)[:, None, None, None, :]
    iw = jnp.clip(jnp.minimum(px2[..., None], gx2)
                  - jnp.maximum(px1[..., None], gx1), 0.0, None)
    ih = jnp.clip(jnp.minimum(py2[..., None], gy2)
                  - jnp.maximum(py1[..., None], gy1), 0.0, None)
    inter = iw * ih
    area_p = (pw * ph)[..., None]
    area_g = (gt_box[:, :, 2] * gt_box[:, :, 3])[:, None, None, None, :]
    iou = inter / (area_p + area_g - inter + 1e-9)
    iou = iou * valid[:, None, None, None, :]
    ignore = (jnp.max(iou, axis=-1) > ignore_thresh)

    tobj = jnp.zeros((n, mask_num * h * w))
    tobj_w = jnp.zeros((n, mask_num * h * w))
    upd = in_head * gt_score
    tobj = tobj.at[jnp.arange(n)[:, None], gt_idx].max(in_head)
    tobj_w = tobj_w.at[jnp.arange(n)[:, None], gt_idx].max(upd)
    tobj = tobj.reshape(n, mask_num, h, w)
    tobj_w = tobj_w.reshape(n, mask_num, h, w)
    obj_weight = jnp.where(tobj > 0, tobj_w,
                           jnp.where(ignore, 0.0, 1.0))
    loss_obj = _bce(obj_logit, tobj) * obj_weight

    per_sample = (loss_xy.sum(-1) + loss_wh.sum(-1) + loss_cls.sum(-1)
                  + loss_obj.sum((1, 2, 3)))
    return per_sample


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    if gt_score is None:
        from ..ops.creation import ones
        gt_score = ones(list(gt_box.shape[:2]), "float32")
    return _yolo_loss(x, gt_box, gt_label, gt_score,
                      anchors=tuple(anchors), anchor_mask=tuple(anchor_mask),
                      class_num=class_num, ignore_thresh=ignore_thresh,
                      downsample_ratio=downsample_ratio,
                      use_label_smooth=use_label_smooth,
                      scale_x_y=scale_x_y)


def _bilinear_sample(img, y, x):
    """img [C,H,W]; y,x [...]: bilinear values [C, ...] with zero padding
    outside (reference deformable_conv/roi_align bilinear)."""
    c, h, w = img.shape
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1 = y - y0
    wx1 = x - x0
    wy0, wx0 = 1.0 - wy1, 1.0 - wx1

    def tap(yy, xx):
        inside = ((yy >= 0) & (yy <= h - 1) & (xx >= 0)
                  & (xx <= w - 1))
        yc = jnp.clip(yy, 0, h - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, w - 1).astype(jnp.int32)
        vals = img[:, yc, xc]  # [C, ...]
        return vals * inside.astype(img.dtype)

    return (tap(y0, x0) * (wy0 * wx0) + tap(y0, x1) * (wy0 * wx1)
            + tap(y1, x0) * (wy1 * wx0) + tap(y1, x1) * (wy1 * wx1))


@register_op("deformable_conv")
def _deform_conv2d(x, offset, weight, mask, *, stride, padding, dilation,
                   deformable_groups, groups, has_mask):
    """Reference: operators/deformable_conv_op.h (v2 modulated when mask
    given). Bilinear sampling at offset taps, then contraction — the
    sampling is a gather XLA vectorizes; the contraction hits the MXU."""
    import jax
    n, cin, h, w = x.shape
    cout, cin_g, kh, kw = weight.shape
    sh, sw = stride
    ph, pw = padding
    dh, dw = dilation
    ho = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    wo = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1

    base_y = (jnp.arange(ho) * sh - ph)[:, None, None]   # [ho,1,1]
    base_x = (jnp.arange(wo) * sw - pw)[None, :, None]   # [1,wo,1]
    tap_dy = (jnp.arange(kh) * dh)[None, None, :, None]  # [1,1,kh,1]
    tap_dx = (jnp.arange(kw) * dw)[None, None, None, :]  # [1,1,1,kw]

    off = offset.reshape(n, deformable_groups, kh * kw, 2, ho, wo)
    if has_mask:
        m = mask.reshape(n, deformable_groups, kh * kw, ho, wo)

    cpg = cin // deformable_groups  # channels per deformable group

    def sample_one(img_n, off_n, mask_n):
        cols = []
        for g in range(deformable_groups):
            img = img_n[g * cpg:(g + 1) * cpg]
            oy = off_n[g, :, 0]  # [kh*kw, ho, wo]
            ox = off_n[g, :, 1]
            # positions: [kh*kw, ho, wo]
            ky = jnp.repeat(jnp.arange(kh), kw)
            kx = jnp.tile(jnp.arange(kw), kh)
            pos_y = (base_y.reshape(1, ho, 1) + (ky * dh).reshape(-1, 1, 1)
                     + oy)
            pos_x = (base_x.reshape(1, 1, wo) + (kx * dw).reshape(-1, 1, 1)
                     + ox)
            sampled = _bilinear_sample(img, pos_y, pos_x)  # [cpg,k2,ho,wo]
            if has_mask:
                sampled = sampled * mask_n[g][None]
            cols.append(sampled)
        return jnp.concatenate(cols, axis=0)  # [cin, k2, ho, wo]

    cols = jax.vmap(sample_one)(x, off, m if has_mask else
                                jnp.zeros((n, 1, 1, 1, 1)))
    # cols [N, cin, kh*kw, ho, wo] x weight [cout, cin_g, kh, kw]
    wmat = weight.reshape(cout, cin_g * kh * kw)
    cg = cin // groups
    outs = []
    for g in range(groups):
        col_g = cols[:, g * cg:(g + 1) * cg].reshape(n, cg * kh * kw, ho, wo)
        out_g = jnp.einsum("nkhw,ok->nohw", col_g,
                           wmat[g * (cout // groups):(g + 1)
                                * (cout // groups)])
        outs.append(out_g)
    return jnp.concatenate(outs, axis=1)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    def _pair(v):
        return tuple(v) if isinstance(v, (list, tuple)) else (v, v)
    out = _deform_conv2d(x, offset, weight, mask,
                         stride=_pair(stride), padding=_pair(padding),
                         dilation=_pair(dilation),
                         deformable_groups=deformable_groups, groups=groups,
                         has_mask=mask is not None)
    if bias is not None:
        from ..ops import math as math_ops
        out = out + bias.reshape([1, -1, 1, 1])
    return out


class DeformConv2D(Layer):
    """Reference: vision/ops.py:621 DeformConv2D."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        ks = kernel_size if isinstance(kernel_size, (list, tuple)) else \
            (kernel_size, kernel_size)
        self._attrs = dict(stride=stride, padding=padding, dilation=dilation,
                           deformable_groups=deformable_groups, groups=groups)
        fan_in = (in_channels // groups) * ks[0] * ks[1]
        self.weight = self.create_parameter(
            (out_channels, in_channels // groups) + tuple(ks),
            attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.KaimingNormal(fan_in=fan_in))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def forward(self, x, offset, mask=None):
        return deform_conv2d(x, offset, self.weight, self.bias,
                             mask=mask, **self._attrs)


@register_op("roi_align")
def _roi_align(x, boxes, box_batch_idx, *, output_size, spatial_scale,
               sampling_ratio, aligned):
    """Reference: operators/roi_align_op.h — average of bilinear samples
    over each output bin."""
    import jax
    ph, pw = output_size
    off = 0.5 if aligned else 0.0

    def one_roi(box, bidx):
        img = x[bidx]  # [C,H,W]
        x1 = box[0] * spatial_scale - off
        y1 = box[1] * spatial_scale - off
        x2 = box[2] * spatial_scale - off
        y2 = box[3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        s = sampling_ratio if sampling_ratio > 0 else 2
        # sample grid [ph, s] x [pw, s]
        iy = (jnp.arange(ph)[:, None] * bin_h + y1
              + (jnp.arange(s)[None, :] + 0.5) * bin_h / s)  # [ph,s]
        ix = (jnp.arange(pw)[:, None] * bin_w + x1
              + (jnp.arange(s)[None, :] + 0.5) * bin_w / s)  # [pw,s]
        yy = iy.reshape(-1)[:, None]  # [ph*s,1]
        xx = ix.reshape(-1)[None, :]  # [1,pw*s]
        vals = _bilinear_sample(img, jnp.broadcast_to(yy, (ph * s, pw * s)),
                                jnp.broadcast_to(xx, (ph * s, pw * s)))
        vals = vals.reshape(-1, ph, s, pw, s)
        return vals.mean((2, 4))  # [C, ph, pw]

    return jax.vmap(one_roi)(boxes, box_batch_idx)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    nums = np.asarray(boxes_num.numpy() if isinstance(boxes_num, Tensor)
                      else boxes_num).astype("int64")
    batch_idx = np.repeat(np.arange(len(nums)), nums).astype("int32")
    return _roi_align(x, boxes, jnp.asarray(batch_idx),
                      output_size=tuple(output_size),
                      spatial_scale=spatial_scale,
                      sampling_ratio=sampling_ratio, aligned=aligned)


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Greedy NMS (reference: detection/multiclass_nms_op / nms util).
    Host-side: sequential suppression is an inference post-process.
    Returns kept indices sorted by score desc."""
    if categories is not None and category_idxs is None:
        raise ValueError("nms: `categories` requires `category_idxs`")
    b = boxes.numpy() if isinstance(boxes, Tensor) else np.asarray(boxes)
    if scores is None:
        order = np.arange(len(b))
    else:
        s = scores.numpy() if isinstance(scores, Tensor) else \
            np.asarray(scores)
        order = np.argsort(-s)
    if category_idxs is not None:
        cats = category_idxs.numpy() if isinstance(category_idxs, Tensor) \
            else np.asarray(category_idxs)
    else:
        cats = np.zeros(len(b), np.int64)

    x1, y1, x2, y2 = b[:, 0], b[:, 1], b[:, 2], b[:, 3]
    areas = np.clip(x2 - x1, 0, None) * np.clip(y2 - y1, 0, None)
    keep = []
    suppressed = np.zeros(len(b), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(x1[i], x1)
        yy1 = np.maximum(y1[i], y1)
        xx2 = np.minimum(x2[i], x2)
        yy2 = np.minimum(y2[i], y2)
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        iou = inter / (areas[i] + areas - inter + 1e-9)
        suppressed |= (iou > iou_threshold) & (cats == cats[i])
        suppressed[i] = True
    keep = np.asarray(keep, np.int64)
    if categories is not None:
        # paddle.vision.ops.nms semantics: suppression ran per category
        # above (cats==cats[i] mask); `categories` then restricts the
        # output and top_k applies GLOBALLY to the merged score-sorted set
        cat_arr = np.asarray(categories.numpy()
                             if isinstance(categories, Tensor)
                             else categories).reshape(-1)
        cat_set = {int(c) for c in cat_arr}
        keep = keep[np.isin(cats[keep], list(cat_set))]
    if top_k is not None:
        keep = keep[:top_k]  # keep is already score-descending
    return Tensor(keep)


def read_file(filename, name=None):
    """Reference: vision/ops.py:810 — raw bytes as uint8 tensor."""
    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(np.frombuffer(data, np.uint8).copy())


def decode_jpeg(x, mode="unchanged", name=None):
    """Reference: vision/ops.py:855 — decode jpeg bytes to CHW uint8.
    Uses PIL (no nvjpeg on TPU hosts)."""
    import io
    try:
        from PIL import Image
    except ImportError as e:
        raise RuntimeError("decode_jpeg requires PIL in this build") from e
    data = bytes(np.asarray(x.numpy() if isinstance(x, Tensor) else x,
                            np.uint8))
    img = Image.open(io.BytesIO(data))
    if mode == "gray":
        img = img.convert("L")
        arr = np.asarray(img)[None]
    else:
        img = img.convert("RGB") if mode == "rgb" else img
        arr = np.asarray(img)
        arr = arr[None] if arr.ndim == 2 else arr.transpose(2, 0, 1)
    return Tensor(arr.copy())
