"""paddle.vision equivalent (reference: python/paddle/vision/)."""
from . import models  # noqa: F401
from . import datasets  # noqa: F401
from . import transforms  # noqa: F401
from . import ops  # noqa: F401


_image_backend = ["pil"]


def set_image_backend(backend):
    """Reference: paddle.vision.set_image_backend ('pil'|'cv2')."""
    if backend not in ("pil", "cv2"):
        raise ValueError(f"unknown image backend {backend!r}")
    _image_backend[0] = backend


def get_image_backend():
    return _image_backend[0]


def image_load(path, backend=None):
    """Reference: paddle.vision.image_load."""
    backend = backend or _image_backend[0]
    if backend == "cv2":
        raise RuntimeError("cv2 is not available in this build; use 'pil'")
    from PIL import Image
    return Image.open(path)
