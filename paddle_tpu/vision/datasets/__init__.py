"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: MNIST/Cifar load from local cache files when
present; FakeData provides deterministic synthetic data for tests and
benchmarks (shape-compatible with the real datasets).
"""
import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME


class FakeData(Dataset):
    """Deterministic synthetic dataset, shape-compatible stand-in."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28),
                 num_classes=10, dtype="float32", seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.dtype = dtype
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.standard_normal(
            (num_samples,) + self.image_shape).astype(dtype)
        self._labels = self._rng.randint(
            0, num_classes, (num_samples, 1)).astype("int64")

    def __getitem__(self, idx):
        return self._images[idx], self._labels[idx]

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """Reference: vision/datasets/mnist.py. Reads idx-format files from
    DATA_HOME/mnist; falls back to FakeData when absent (offline env)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        base = os.path.join(DATA_HOME, "mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._load(image_path, label_path)
        else:
            fake = FakeData(60000 if mode == "train" else 10000,
                            (1, 28, 28), 10)
            self.images = fake._images.reshape(-1, 28, 28)
            self.labels = fake._labels
        self._fake = not (os.path.exists(image_path)
                          and os.path.exists(label_path))

    @staticmethod
    def _load(image_path, label_path):
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype("int64")
        images = images.astype("float32") / 255.0
        return images, labels.reshape(-1, 1)

    def __getitem__(self, idx):
        img = self.images[idx].reshape(1, 28, 28).astype("float32")
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    """Reference: vision/datasets/cifar.py; synthetic fallback offline."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(DATA_HOME, "cifar",
                                              "cifar-10-python.tar.gz")
        if os.path.exists(data_file):
            self.images, self.labels = self._load_tar(data_file, mode)
        else:
            fake = FakeData(50000 if mode == "train" else 10000,
                            (3, 32, 32), 10)
            self.images = fake._images
            self.labels = fake._labels

    @staticmethod
    def _load_tar(data_file, mode, label_key=b"labels"):
        import pickle
        import tarfile
        want = "test_batch" if mode != "train" else "data_batch"
        if label_key == b"fine_labels":
            want = "test" if mode != "train" else "train"
        images, labels = [], []
        with tarfile.open(data_file) as tf:
            for member in sorted(tf.getnames()):
                if want in os.path.basename(member):
                    batch = pickle.load(tf.extractfile(member),
                                        encoding="bytes")
                    images.append(batch[b"data"].reshape(-1, 3, 32, 32)
                                  .astype("float32") / 255.0)
                    labels.extend(batch[label_key])
        return (np.concatenate(images),
                np.asarray(labels, "int64").reshape(-1, 1))

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        data_file = data_file or os.path.join(DATA_HOME, "cifar",
                                              "cifar-100-python.tar.gz")
        if os.path.exists(data_file):
            self.images, self.labels = self._load_tar(data_file, mode,
                                                      b"fine_labels")
        else:
            fake = FakeData(50000 if mode == "train" else 10000,
                            (3, 32, 32), 100, seed=1)
            self.images, self.labels = fake._images, fake._labels


class FashionMNIST(MNIST):
    """Reference: vision/datasets/mnist.py FashionMNIST — same idx format,
    different archive directory."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        base = os.path.join(DATA_HOME, "fashion-mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{prefix}-labels-idx1-ubyte.gz")
        super().__init__(image_path, label_path, mode, transform, download,
                         backend)


def _default_image_loader(path):
    if path.endswith(".npy"):
        return np.load(path)
    try:
        from PIL import Image
        with Image.open(path) as img:
            return np.asarray(img.convert("RGB"), np.float32) / 255.0
    except ImportError as e:
        raise RuntimeError(
            f"cannot load {path}: PIL unavailable; use .npy files") from e


IMG_EXTENSIONS = (".jpg", ".jpeg", ".png", ".ppm", ".bmp", ".npy")


class DatasetFolder(Dataset):
    """Reference: vision/datasets/folder.py DatasetFolder — one class per
    subdirectory, samples = (image, class_index)."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_image_loader
        self.transform = transform
        extensions = tuple(extensions) if extensions else IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            cdir = os.path.join(root, c)
            for dirpath, _, files in sorted(os.walk(cdir)):
                for fname in sorted(files):
                    path = os.path.join(dirpath, fname)
                    ok = (is_valid_file(path) if is_valid_file
                          else fname.lower().endswith(extensions))
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))
        if not self.samples:
            raise RuntimeError(f"no valid samples under {root}")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        img = self.loader(path)
        if self.transform is not None:
            img = self.transform(img)
        return img, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    """Reference: folder.py ImageFolder — flat listing, images only."""

    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.loader = loader or _default_image_loader
        self.transform = transform
        extensions = tuple(extensions) if extensions else IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, files in sorted(os.walk(root)):
            for fname in sorted(files):
                path = os.path.join(dirpath, fname)
                ok = (is_valid_file(path) if is_valid_file
                      else fname.lower().endswith(extensions))
                if ok:
                    self.samples.append(path)
        if not self.samples:
            raise RuntimeError(f"no valid samples under {root}")

    def __getitem__(self, idx):
        img = self.loader(self.samples[idx])
        if self.transform is not None:
            img = self.transform(img)
        return [img]

    def __len__(self):
        return len(self.samples)


class Flowers(Dataset):
    """Reference: vision/datasets/flowers.py (102 classes); synthetic
    fallback offline."""

    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode="train", transform=None, download=True, backend=None):
        self.transform = transform
        n = {"train": 6149, "valid": 1020, "test": 1020}.get(mode, 1020)
        fake = FakeData(min(n, 256), (3, 224, 224), 102, seed=2)
        self.images, self.labels = fake._images, fake._labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class VOC2012(Dataset):
    """Reference: vision/datasets/voc2012.py (segmentation pairs);
    synthetic fallback offline: (image, mask) with 21 classes."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        rng = np.random.RandomState(3)
        n = 64
        self.images = rng.standard_normal((n, 3, 64, 64)).astype("float32")
        self.masks = rng.randint(0, 21, (n, 64, 64)).astype("int64")

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.masks[idx]

    def __len__(self):
        return len(self.images)
