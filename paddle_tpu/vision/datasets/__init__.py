"""Vision datasets (reference: python/paddle/vision/datasets/).

Zero-egress environment: MNIST/Cifar load from local cache files when
present; FakeData provides deterministic synthetic data for tests and
benchmarks (shape-compatible with the real datasets).
"""
import gzip
import os
import struct

import numpy as np

from ...io.dataset import Dataset
from ...utils.download import DATA_HOME


class FakeData(Dataset):
    """Deterministic synthetic dataset, shape-compatible stand-in."""

    def __init__(self, num_samples=1024, image_shape=(1, 28, 28),
                 num_classes=10, dtype="float32", seed=0):
        self.num_samples = num_samples
        self.image_shape = tuple(image_shape)
        self.num_classes = num_classes
        self.dtype = dtype
        self._rng = np.random.RandomState(seed)
        self._images = self._rng.standard_normal(
            (num_samples,) + self.image_shape).astype(dtype)
        self._labels = self._rng.randint(
            0, num_classes, (num_samples, 1)).astype("int64")

    def __getitem__(self, idx):
        return self._images[idx], self._labels[idx]

    def __len__(self):
        return self.num_samples


class MNIST(Dataset):
    """Reference: vision/datasets/mnist.py. Reads idx-format files from
    DATA_HOME/mnist; falls back to FakeData when absent (offline env)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        base = os.path.join(DATA_HOME, "mnist")
        prefix = "train" if mode == "train" else "t10k"
        image_path = image_path or os.path.join(
            base, f"{prefix}-images-idx3-ubyte.gz")
        label_path = label_path or os.path.join(
            base, f"{prefix}-labels-idx1-ubyte.gz")
        if os.path.exists(image_path) and os.path.exists(label_path):
            self.images, self.labels = self._load(image_path, label_path)
        else:
            fake = FakeData(60000 if mode == "train" else 10000,
                            (1, 28, 28), 10)
            self.images = fake._images.reshape(-1, 28, 28)
            self.labels = fake._labels
        self._fake = not (os.path.exists(image_path)
                          and os.path.exists(label_path))

    @staticmethod
    def _load(image_path, label_path):
        with gzip.open(image_path, "rb") as f:
            magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
            images = np.frombuffer(f.read(), np.uint8).reshape(n, rows, cols)
        with gzip.open(label_path, "rb") as f:
            magic, n = struct.unpack(">II", f.read(8))
            labels = np.frombuffer(f.read(), np.uint8).astype("int64")
        images = images.astype("float32") / 255.0
        return images, labels.reshape(-1, 1)

    def __getitem__(self, idx):
        img = self.images[idx].reshape(1, 28, 28).astype("float32")
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar10(Dataset):
    """Reference: vision/datasets/cifar.py; synthetic fallback offline."""

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.transform = transform
        fake = FakeData(50000 if mode == "train" else 10000, (3, 32, 32), 10)
        self.images = fake._images
        self.labels = fake._labels

    def __getitem__(self, idx):
        img = self.images[idx]
        if self.transform is not None:
            img = self.transform(img)
        return img, self.labels[idx]

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        super().__init__(data_file, mode, transform, download, backend)
        fake = FakeData(len(self.images), (3, 32, 32), 100, seed=1)
        self.labels = fake._labels
