"""paddle.text.datasets equivalents (reference:
python/paddle/text/datasets/: Conll05st, Imdb, Imikolov, Movielens,
UCIHousing, WMT14, WMT16).

Zero-egress environment: each dataset loads from local cache files under
DATA_HOME when present, else builds a deterministic synthetic corpus with
the same sample structure (word-id sequences / rating tuples / feature
rows) so pipelines and tests run identically offline.
"""
import os

import numpy as np

from ..io.dataset import Dataset
from ..utils.download import DATA_HOME


def _rng(seed):
    return np.random.RandomState(seed)


class Imdb(Dataset):
    """Sentiment pairs (ids, label) (reference: datasets/imdb.py)."""

    def __init__(self, data_file=None, mode="train", cutoff=150):
        r = _rng(10 if mode == "train" else 11)
        n = 512
        self.word_idx = {f"w{i}": i for i in range(cutoff)}
        lens = r.randint(5, 64, n)
        self.docs = [r.randint(0, cutoff, l).astype("int64") for l in lens]
        self.labels = r.randint(0, 2, n).astype("int64")

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """PTB-style n-gram tuples (reference: datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50):
        r = _rng(12 if mode == "train" else 13)
        vocab = 2000
        self.word_idx = {f"w{i}": i for i in range(vocab)}
        n = 1024
        if data_type.upper() == "NGRAM":
            self.data = [tuple(r.randint(0, vocab, window_size))
                         for _ in range(n)]
        else:  # SEQ
            self.data = [(r.randint(0, vocab, 10).astype("int64"),
                          r.randint(0, vocab, 10).astype("int64"))
                         for _ in range(n)]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class Movielens(Dataset):
    """(user_id, gender, age, job, movie_id, title_ids, categories,
    rating) tuples (reference: datasets/movielens.py)."""

    def __init__(self, data_file=None, mode="train", test_ratio=0.1,
                 rand_seed=0):
        r = _rng(rand_seed + (0 if mode == "train" else 1))
        n = 512
        self.data = [(
            np.array([r.randint(1, 6041)], "int64"),      # user id
            np.array([r.randint(0, 2)], "int64"),         # gender
            np.array([r.randint(0, 7)], "int64"),         # age bucket
            np.array([r.randint(0, 21)], "int64"),        # job
            np.array([r.randint(1, 3953)], "int64"),      # movie id
            r.randint(0, 5000, 4).astype("int64"),        # title word ids
            r.randint(0, 19, 3).astype("int64"),          # category ids
            np.array([float(r.randint(1, 6))], "float32"),  # rating
        ) for _ in range(n)]

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class UCIHousing(Dataset):
    """13-feature housing rows (reference: datasets/uci_housing.py);
    loads the real space-separated file from DATA_HOME when present."""

    def __init__(self, data_file=None, mode="train"):
        data_file = data_file or os.path.join(DATA_HOME, "uci_housing",
                                              "housing.data")
        if os.path.exists(data_file):
            raw = np.loadtxt(data_file).astype("float32")
        else:
            r = _rng(20)
            feats = r.standard_normal((506, 13)).astype("float32")
            prices = (feats @ r.standard_normal((13, 1)) + 22.5)
            raw = np.concatenate([feats, prices.astype("float32")], axis=1)
        # reference normalizes features then splits 80/20
        feats, target = raw[:, :-1], raw[:, -1:]
        feats = (feats - feats.mean(0)) / (feats.std(0) + 1e-8)
        split = int(len(raw) * 0.8)
        if mode == "train":
            self.data = np.concatenate([feats[:split], target[:split]], 1)
        else:
            self.data = np.concatenate([feats[split:], target[split:]], 1)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1].astype("float32"), row[-1:].astype("float32")

    def __len__(self):
        return len(self.data)


class Conll05st(Dataset):
    """SRL tuples: (word_ids, ctx_n2..ctx_p2, verb, mark, label seq)
    (reference: datasets/conll05.py)."""

    def __init__(self, data_file=None, word_dict_file=None,
                 verb_dict_file=None, target_dict_file=None, mode="train"):
        r = _rng(30)
        vocab, labels, n = 5000, 67, 256
        self.word_dict = {f"w{i}": i for i in range(vocab)}
        self.label_dict = {f"l{i}": i for i in range(labels)}
        self.predicate_dict = {f"v{i}": i for i in range(3000)}
        self.data = []
        for _ in range(n):
            ln = int(r.randint(4, 32))
            words = r.randint(0, vocab, ln).astype("int64")
            sample = [words]
            for _ in range(5):  # ctx windows
                sample.append(r.randint(0, vocab, ln).astype("int64"))
            sample.append(r.randint(0, 3000, ln).astype("int64"))  # verb
            sample.append(r.randint(0, 2, ln).astype("int64"))     # mark
            sample.append(r.randint(0, labels, ln).astype("int64"))
            self.data.append(tuple(sample))

    def get_dict(self):
        return self.word_dict, self.predicate_dict, self.label_dict

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class _WMTBase(Dataset):
    def __init__(self, seed, dict_size, mode="train", trg_dict_size=None):
        r = _rng(seed if mode == "train" else seed + 1)
        self._dict_size = dict_size
        trg_size = trg_dict_size or dict_size
        n = 256
        self.data = []
        for _ in range(n):
            sl, tl = int(r.randint(4, 24)), int(r.randint(4, 24))
            src = r.randint(0, dict_size, sl).astype("int64")
            trg = r.randint(0, trg_size, tl).astype("int64")
            trg_next = np.roll(trg, -1)
            self.data.append((src, trg, trg_next))

    def __getitem__(self, idx):
        return self.data[idx]

    def __len__(self):
        return len(self.data)


class WMT14(_WMTBase):
    """Reference: datasets/wmt14.py (en→fr id triples)."""

    def __init__(self, data_file=None, mode="train", dict_size=30000):
        super().__init__(40, dict_size, mode)

    def get_dict(self, lang="en", reverse=False):
        d = {f"{lang}{i}": i for i in range(self._dict_size)}
        return {v: k for k, v in d.items()} if reverse else d


class WMT16(_WMTBase):
    """Reference: datasets/wmt16.py (en↔de, trg_next shifted)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=30000,
                 trg_dict_size=30000, lang="en"):
        super().__init__(50, src_dict_size, mode, trg_dict_size)
        self._trg_dict_size = trg_dict_size

    def get_dict(self, lang="en", reverse=False):
        size = self._dict_size if lang == "en" else self._trg_dict_size
        d = {f"{lang}{i}": i for i in range(size)}
        return {v: k for k, v in d.items()} if reverse else d
