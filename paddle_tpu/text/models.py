"""BERT / GPT model families.

Reference anchors: BERT-base pretraining and GPT-3 1.3B hybrid-parallel
configs (BASELINE.md #3/#5; reference TP layers
fleet/meta_parallel/parallel_layers/mp_layers.py). Models are built from
paddle_tpu.nn layers; when a hybrid mesh is active, linear/embedding
layers use the tensor-parallel variants so GSPMD shards them over 'mp'.
"""
import math

from .. import nn
from ..ops import creation, manipulation, math as math_ops, nn_ops
from ..distributed import topology
from ..distributed.fleet.meta_parallel.mp_layers import (
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    shard_constraint,
)


class TransformerLMConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None, max_seq_len=1024,
                 dropout=0.1, use_mp=False, tie_embeddings=True,
                 use_flash_attention=True, initializer_range=0.02):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or hidden_size * 4
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.use_mp = use_mp
        self.tie_embeddings = tie_embeddings
        self.use_flash_attention = use_flash_attention
        self.initializer_range = initializer_range


def _mp_active():
    mesh = topology.get_mesh()
    return mesh is not None and int(mesh.shape.get("mp", 1)) > 1


class SelfAttention(nn.Layer):
    """Fused-QKV attention; column-parallel QKV + row-parallel output when
    TP is active (the Megatron split, reference mp_layers.py)."""

    def __init__(self, cfg, causal):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        self.causal = causal
        self.dropout = cfg.dropout
        self.use_flash = cfg.use_flash_attention
        use_mp = cfg.use_mp and _mp_active()
        if use_mp:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.out = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.out = nn.Linear(h, h)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = self.qkv(x)
        qkv = manipulation.reshape(qkv, (b, s, 3, self.num_heads,
                                         self.head_dim))
        qkv = manipulation.transpose(qkv, (2, 0, 3, 1, 4))
        q, k, v = manipulation.unbind(qkv, axis=0)
        from ..ops import attention as attn_ops
        o = attn_ops.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, is_causal=self.causal)
        o = manipulation.transpose(o, (0, 2, 1, 3))
        o = manipulation.reshape(o, (b, s, h))
        o = self.out(o)
        if self.dropout:
            o = nn_ops.dropout(o, p=self.dropout, training=self.training)
        return o


class MLP(nn.Layer):
    def __init__(self, cfg, activation="gelu"):
        super().__init__()
        h, inter = cfg.hidden_size, cfg.intermediate_size
        use_mp = cfg.use_mp and _mp_active()
        if use_mp:
            self.fc1 = ColumnParallelLinear(h, inter, gather_output=False)
            self.fc2 = RowParallelLinear(inter, h, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, inter)
            self.fc2 = nn.Linear(inter, h)
        self.act = activation
        self.dropout = cfg.dropout

    def forward(self, x):
        x = self.fc1(x)
        x = nn_ops.gelu(x, approximate=True) if self.act == "gelu" else \
            nn_ops.relu(x)
        x = self.fc2(x)
        if self.dropout:
            x = nn_ops.dropout(x, p=self.dropout, training=self.training)
        return x


class Block(nn.Layer):
    def __init__(self, cfg, causal, pre_norm=True):
        super().__init__()
        self.pre_norm = pre_norm
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = SelfAttention(cfg, causal)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = MLP(cfg)

    def forward(self, x, attn_mask=None):
        if self.pre_norm:  # GPT style
            x = math_ops.add(x, self.attn(self.ln1(x), attn_mask))
            x = math_ops.add(x, self.mlp(self.ln2(x)))
        else:  # BERT style post-norm
            x = self.ln1(math_ops.add(x, self.attn(x, attn_mask)))
            x = self.ln2(math_ops.add(x, self.mlp(x)))
        return x


class _TransformerCore(nn.Layer):
    def __init__(self, cfg, causal, pre_norm, with_token_type=False):
        super().__init__()
        self.cfg = cfg
        use_mp = cfg.use_mp and _mp_active()
        # reference init (BERT/GPT initializer_range=0.02): with tied
        # embeddings, N(0,1) rows would give logits of scale
        # sqrt(hidden) and an untrainable initial loss
        from ..nn import initializer as init_mod
        emb_attr = init_mod.ParamAttr(
            initializer=init_mod.Normal(0.0, cfg.initializer_range))
        if use_mp:
            self.word_embeddings = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size, weight_attr=emb_attr)
        else:
            self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                                cfg.hidden_size,
                                                weight_attr=emb_attr)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len,
                                                cfg.hidden_size,
                                                weight_attr=emb_attr)
        self.token_type_embeddings = nn.Embedding(
            2, cfg.hidden_size, weight_attr=emb_attr) \
            if with_token_type else None
        self.blocks = nn.LayerList(
            [Block(cfg, causal, pre_norm) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        self.pre_norm = pre_norm

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        s = input_ids.shape[1]
        pos = creation.arange(0, s, dtype="int64")
        x = self.word_embeddings(input_ids)
        x = math_ops.add(x, self.position_embeddings(pos))
        if self.token_type_embeddings is not None and token_type_ids is not None:
            x = math_ops.add(x, self.token_type_embeddings(token_type_ids))
        if self.cfg.dropout:
            x = nn_ops.dropout(x, p=self.cfg.dropout, training=self.training)
        for blk in self.blocks:
            x = blk(x, attn_mask)
        if self.pre_norm:
            x = self.ln_f(x)
        return x


class GPTModel(_TransformerCore):
    """Decoder-only causal LM core (GPT-3 style: pre-norm)."""

    def __init__(self, cfg):
        super().__init__(cfg, causal=True, pre_norm=True)


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        return self._head_loss(h, labels)

    def _head_loss(self, h, labels=None):
        if self.cfg.tie_embeddings:
            logits = math_ops.matmul(h, self.gpt.word_embeddings.weight,
                                     transpose_y=True)
        else:
            logits = self.lm_head(h)
        if labels is None:
            return logits
        loss = nn_ops.cross_entropy(
            manipulation.reshape(logits, (-1, self.cfg.vocab_size)),
            manipulation.reshape(labels, (-1,)))
        return loss

    def pp_segments(self):
        """Pipeline-parallel segmentation (see PipelineParallel): edge
        segments run GSPMD on the full mesh — which makes the tied
        embedding (used in pre AND post) trivially shared — and the
        transformer blocks are the pipelined homogeneous run."""
        core = self.gpt

        def pre(input_ids):
            s = input_ids.shape[1]
            pos = creation.arange(0, s, dtype="int64")
            x = core.word_embeddings(input_ids)
            x = math_ops.add(x, core.position_embeddings(pos))
            if core.cfg.dropout:
                x = nn_ops.dropout(x, p=core.cfg.dropout,
                                   training=core.training)
            return x

        def post(h, labels=None):
            h = core.ln_f(h)
            return self._head_loss(h, labels)

        return {"pre": pre, "blocks": list(core.blocks), "post": post}


class BertModel(_TransformerCore):
    """Encoder core (BERT style: post-norm, token types)."""

    def __init__(self, cfg):
        super().__init__(cfg, causal=False, pre_norm=False,
                         with_token_type=True)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        h = super().forward(input_ids, token_type_ids, attn_mask)
        pooled = nn_ops.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference pretraining objective for config 3)."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = nn.LayerNorm(cfg.hidden_size)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, masked_lm_labels=None,
                next_sentence_labels=None):
        h, pooled = self.bert(input_ids, token_type_ids)
        t = nn_ops.gelu(self.mlm_transform(h), approximate=True)
        t = self.mlm_ln(t)
        logits = math_ops.matmul(t, self.bert.word_embeddings.weight,
                                 transpose_y=True)
        if masked_lm_labels is None:
            return logits
        mlm_loss = nn_ops.cross_entropy(
            manipulation.reshape(logits, (-1, self.cfg.vocab_size)),
            manipulation.reshape(masked_lm_labels, (-1,)),
            ignore_index=-1)
        if next_sentence_labels is not None:
            nsp_logits = self.nsp_head(pooled)
            nsp_loss = nn_ops.cross_entropy(
                nsp_logits, manipulation.reshape(next_sentence_labels, (-1,)))
            return math_ops.add(mlm_loss, nsp_loss)
        return mlm_loss


def bert_base(vocab_size=30522, max_seq_len=512, **kwargs):
    cfg = TransformerLMConfig(vocab_size=vocab_size, hidden_size=768,
                              num_layers=12, num_heads=12,
                              max_seq_len=max_seq_len, **kwargs)
    return BertForPretraining(cfg)


def gpt3_1p3b(vocab_size=50304, max_seq_len=1024, **kwargs):
    """GPT-3 1.3B: 24 layers, hidden 2048, 16 heads (BASELINE config 5)."""
    cfg = TransformerLMConfig(vocab_size=vocab_size, hidden_size=2048,
                              num_layers=24, num_heads=16,
                              max_seq_len=max_seq_len, **kwargs)
    return GPTForCausalLM(cfg)
