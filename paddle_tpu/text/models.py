"""BERT / GPT model families.

Reference anchors: BERT-base pretraining and GPT-3 1.3B hybrid-parallel
configs (BASELINE.md #3/#5; reference TP layers
fleet/meta_parallel/parallel_layers/mp_layers.py). Models are built from
paddle_tpu.nn layers; when a hybrid mesh is active, linear/embedding
layers use the tensor-parallel variants so GSPMD shards them over 'mp'.
"""
import math

from .. import nn
from ..ops import creation, manipulation, math as math_ops, nn_ops
from ..distributed import topology
from ..distributed.fleet.meta_parallel.mp_layers import (
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    shard_constraint,
)


class TransformerLMConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, intermediate_size=None, max_seq_len=1024,
                 dropout=0.1, use_mp=False, tie_embeddings=True,
                 use_flash_attention=True, initializer_range=0.02,
                 recompute=False, use_sp=False, sp_mode="ring"):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.intermediate_size = intermediate_size or hidden_size * 4
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.use_mp = use_mp
        self.tie_embeddings = tie_embeddings
        self.use_flash_attention = use_flash_attention
        self.initializer_range = initializer_range
        self.recompute = recompute
        # sequence/context parallelism over the 'sp' mesh axis:
        # attention runs ring (K/V stream the ICI ring, O(S/sp) HBM per
        # chip) or ulysses (head all-to-all) and activations are
        # sequence-sharded — the lever that trains long contexts the
        # chip's HBM cannot hold whole
        self.use_sp = use_sp
        assert sp_mode in ("ring", "ulysses")
        self.sp_mode = sp_mode


def _mp_active():
    mesh = topology.get_mesh()
    return mesh is not None and int(mesh.shape.get("mp", 1)) > 1


def _sp_active():
    mesh = topology.get_mesh()
    return mesh is not None and int(mesh.shape.get("sp", 1)) > 1


class SelfAttention(nn.Layer):
    """Fused-QKV attention; column-parallel QKV + row-parallel output when
    TP is active (the Megatron split, reference mp_layers.py)."""

    def __init__(self, cfg, causal):
        super().__init__()
        h = cfg.hidden_size
        self.num_heads = cfg.num_heads
        self.head_dim = h // cfg.num_heads
        self.causal = causal
        self.dropout = cfg.dropout
        self.use_flash = cfg.use_flash_attention
        self.use_sp = getattr(cfg, "use_sp", False)
        self.sp_mode = getattr(cfg, "sp_mode", "ring")
        use_mp = cfg.use_mp and _mp_active()
        if use_mp:
            self.qkv = ColumnParallelLinear(h, 3 * h, gather_output=False)
            self.out = RowParallelLinear(h, h, input_is_parallel=True)
        else:
            self.qkv = nn.Linear(h, 3 * h)
            self.out = nn.Linear(h, h)

    def forward(self, x, attn_mask=None):
        b, s, h = x.shape
        qkv = self.qkv(x)
        qkv = manipulation.reshape(qkv, (b, s, 3, self.num_heads,
                                         self.head_dim))
        qkv = manipulation.transpose(qkv, (2, 0, 3, 1, 4))
        q, k, v = manipulation.unbind(qkv, axis=0)
        if self.use_sp and attn_mask is None and _sp_active():
            # sequence-parallel kernel over the 'sp' mesh axis (falls
            # back to dense/flash when the mesh has no sp axis); custom
            # masks need the gathered scores and keep the dense path
            from ..distributed.fleet.meta_parallel.sequence_parallel \
                import ring_attention, ulysses_attention
            sp_fn = (ring_attention if self.sp_mode == "ring"
                     else ulysses_attention)
            o = sp_fn(q, k, v, causal=self.causal)
        else:
            from ..ops import attention as attn_ops
            o = attn_ops.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=self.causal)
        o = manipulation.transpose(o, (0, 2, 1, 3))
        o = manipulation.reshape(o, (b, s, h))
        o = self.out(o)
        if self.dropout:
            o = nn_ops.dropout(o, p=self.dropout, training=self.training)
        return o


class MLP(nn.Layer):
    def __init__(self, cfg, activation="gelu"):
        super().__init__()
        h, inter = cfg.hidden_size, cfg.intermediate_size
        use_mp = cfg.use_mp and _mp_active()
        if use_mp:
            self.fc1 = ColumnParallelLinear(h, inter, gather_output=False)
            self.fc2 = RowParallelLinear(inter, h, input_is_parallel=True)
        else:
            self.fc1 = nn.Linear(h, inter)
            self.fc2 = nn.Linear(inter, h)
        self.act = activation
        self.dropout = cfg.dropout

    def forward(self, x):
        x = self.fc1(x)
        x = nn_ops.gelu(x, approximate=True) if self.act == "gelu" else \
            nn_ops.relu(x)
        x = self.fc2(x)
        if self.dropout:
            x = nn_ops.dropout(x, p=self.dropout, training=self.training)
        return x


class Block(nn.Layer):
    def __init__(self, cfg, causal, pre_norm=True):
        super().__init__()
        self.pre_norm = pre_norm
        self.ln1 = nn.LayerNorm(cfg.hidden_size)
        self.attn = SelfAttention(cfg, causal)
        self.ln2 = nn.LayerNorm(cfg.hidden_size)
        self.mlp = MLP(cfg)

    def forward(self, x, attn_mask=None):
        if self.pre_norm:  # GPT style
            x = math_ops.add(x, self.attn(self.ln1(x), attn_mask))
            x = math_ops.add(x, self.mlp(self.ln2(x)))
        else:  # BERT style post-norm
            x = self.ln1(math_ops.add(x, self.attn(x, attn_mask)))
            x = self.ln2(math_ops.add(x, self.mlp(x)))
        return x


class _TransformerCore(nn.Layer):
    def __init__(self, cfg, causal, pre_norm, with_token_type=False):
        super().__init__()
        self.cfg = cfg
        use_mp = cfg.use_mp and _mp_active()
        # reference init (BERT/GPT initializer_range=0.02): with tied
        # embeddings, N(0,1) rows would give logits of scale
        # sqrt(hidden) and an untrainable initial loss
        from ..nn import initializer as init_mod
        emb_attr = init_mod.ParamAttr(
            initializer=init_mod.Normal(0.0, cfg.initializer_range))
        if use_mp:
            self.word_embeddings = VocabParallelEmbedding(
                cfg.vocab_size, cfg.hidden_size, weight_attr=emb_attr)
        else:
            self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                                cfg.hidden_size,
                                                weight_attr=emb_attr)
        self.position_embeddings = nn.Embedding(cfg.max_seq_len,
                                                cfg.hidden_size,
                                                weight_attr=emb_attr)
        self.token_type_embeddings = nn.Embedding(
            2, cfg.hidden_size, weight_attr=emb_attr) \
            if with_token_type else None
        self.blocks = nn.LayerList(
            [Block(cfg, causal, pre_norm) for _ in range(cfg.num_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size)
        self.pre_norm = pre_norm

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        s = input_ids.shape[1]
        pos = creation.arange(0, s, dtype="int64")
        x = self.word_embeddings(input_ids)
        x = math_ops.add(x, self.position_embeddings(pos))
        if self.token_type_embeddings is not None and token_type_ids is not None:
            x = math_ops.add(x, self.token_type_embeddings(token_type_ids))
        if self.cfg.dropout:
            x = nn_ops.dropout(x, p=self.cfg.dropout, training=self.training)
        if getattr(self.cfg, "use_sp", False) and _sp_active():
            # sequence-shard the activations: every elementwise op /
            # LayerNorm / MLP between attentions holds only S/sp of the
            # sequence per chip (GSPMD propagates the layout; the
            # attention kernel reshards to its ring/all-to-all form)
            from ..distributed.fleet.meta_parallel.mp_layers import \
                shard_constraint
            mesh = topology.get_mesh()
            bspec = "dp" if "dp" in mesh.axis_names else None
            x = shard_constraint(x, (bspec, "sp", None))
        use_rc = (getattr(self.cfg, "recompute", False) and self.training
                  and not x.stop_gradient)
        if use_rc:
            from ..distributed.utils_recompute import recompute as _rc
        for blk in self.blocks:
            # per-block activation recompute (reference: fleet recompute
            # over transformer layers) — trades one extra forward per
            # block for O(layers) less live activation memory; the lever
            # that fits seq-4096 training batches on one chip
            x = _rc(blk, x, attn_mask) if use_rc else blk(x, attn_mask)
        if self.pre_norm:
            x = self.ln_f(x)
        return x


class GPTModel(_TransformerCore):
    """Decoder-only causal LM core (GPT-3 style: pre-norm)."""

    def __init__(self, cfg):
        super().__init__(cfg, causal=True, pre_norm=True)


def _decode_forward_builder(num_heads, head_dim, hidden_size):
    """Pure-jax KV-cache decode math shared by generate() AND the
    serving engine (paddle_tpu.serving) — one definition, so the
    continuous-batching engine's greedy tokens match generate() by
    construction. Returns (ln, forward_t):

      forward_t(params, tok [bb, t], pos, kc, vc) -> (logits, kc, vc)

    with kc/vc [L, bb, nh, total, hd]; writes the new K/V at
    pos..pos+t and attends causally over the cache (positions beyond
    the live prefix are masked to exact-zero softmax weight, so stale
    slot contents are invisible)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    nh, hd = num_heads, head_dim

    def ln(x, w, bias):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        return (x - mu) / jnp.sqrt(var + 1e-5) * w + bias

    def block(x, p, kc, vc, pos):
        # x [bb, t, h]; kc/vc [bb, nh, total, hd]; writes at
        # pos..pos+t (bb = batch OR batch*beams OR one pool slot)
        bb, t = x.shape[0], x.shape[1]
        total = kc.shape[2]
        h_ = ln(x, p["ln1_w"], p["ln1_b"])
        qkv = h_ @ p["qkv_w"] + p["qkv_b"]
        qkv = qkv.reshape(bb, t, 3, nh, hd).transpose(2, 0, 3, 1, 4)
        q, k, v = qkv[0], qkv[1], qkv[2]
        z = jnp.int32(0)  # index dtypes must all match under x64
        kc = lax.dynamic_update_slice(kc, k, (z, z, pos, z))
        vc = lax.dynamic_update_slice(vc, v, (z, z, pos, z))
        s = jnp.einsum("bhtd,bhsd->bhts", q, kc) / jnp.sqrt(
            jnp.float32(hd))
        kpos = jnp.arange(total)[None, None, None, :]
        qpos = pos + jnp.arange(t)[None, None, :, None]
        s = jnp.where(kpos <= qpos, s, jnp.float32(-1e30))
        o = jnp.einsum("bhts,bhsd->bhtd",
                       jax.nn.softmax(s, axis=-1), vc)
        o = o.transpose(0, 2, 1, 3).reshape(bb, t, hidden_size)
        x = x + (o @ p["out_w"] + p["out_b"])
        h2 = ln(x, p["ln2_w"], p["ln2_b"])
        m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"],
                        approximate=True)
        return x + (m @ p["fc2_w"] + p["fc2_b"]), kc, vc

    def forward_t(pr, tok, pos, kc, vc):
        # tok [bb, t] int32; kc/vc [L, bb, nh, total, hd]
        t = tok.shape[1]
        x = pr["wemb"][tok] + pr["pemb"][pos + jnp.arange(t)]

        def body(carry, inp):
            x = carry
            p, kcl, vcl = inp
            x, kcl, vcl = block(x, p, kcl, vcl, pos)
            return x, (kcl, vcl)

        x, (kc, vc) = lax.scan(body, x, (pr["stacked"], kc, vc))
        logits = ln(x, pr["lnf_w"], pr["lnf_b"]) @ pr["head"]
        return logits, kc, vc

    return ln, forward_t


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if not cfg.tie_embeddings:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, labels=None):
        h = self.gpt(input_ids)
        return self._head_loss(h, labels)

    def _head_loss(self, h, labels=None):
        mesh = topology.get_mesh()
        mesh_trivial = mesh is None or all(
            int(d) == 1 for d in mesh.shape.values())
        if labels is not None and self.cfg.tie_embeddings \
                and not self.cfg.use_mp and mesh_trivial:
            # fused linear+CE streams vocab tiles through VMEM: the
            # [tokens, vocab] logits tensor never exists in HBM in
            # either direction (ops/fused_ce.py; falls back to the
            # composition below on CPU / unsupported shapes).
            from ..ops.fused_ce import fused_linear_cross_entropy
            flat = manipulation.reshape(labels, (-1,))
            per_tok = fused_linear_cross_entropy(
                manipulation.reshape(h, (-1, self.cfg.hidden_size)),
                self.gpt.word_embeddings.weight, flat)
            # mean over NON-IGNORED tokens, matching cross_entropy's
            # reduction='mean' (a plain mean would scale loss/grads by
            # the valid fraction on padded batches)
            valid = (flat != -100).astype("float32").sum()
            return per_tok.sum() / valid.clip(min=1.0)
        if labels is not None and self.cfg.tie_embeddings \
                and self.cfg.use_mp and mesh is not None:
            # TP: the vocab-sharded fused kernel — each mp shard
            # streams its LOCAL vocab tile through VMEM, then
            # pmax/psum combine the per-shard logsumexp (the
            # c_softmax_with_cross_entropy_op.cu scheme; pp>1 keeps
            # the composition — stages slice the program before the
            # head)
            from ..ops.fused_ce import (fused_linear_cross_entropy_tp,
                                        tp_fused_applicable)
            t = 1
            for d in h.shape[:-1]:
                t *= int(d)
            if tp_fused_applicable(mesh, t, self.cfg.hidden_size,
                                   self.cfg.vocab_size):
                flat = manipulation.reshape(labels, (-1,))
                per_tok = fused_linear_cross_entropy_tp(
                    manipulation.reshape(h, (-1, self.cfg.hidden_size)),
                    self.gpt.word_embeddings.weight, flat, mesh)
                valid = (flat != -100).astype("float32").sum()
                return per_tok.sum() / valid.clip(min=1.0)
        if self.cfg.tie_embeddings:
            logits = math_ops.matmul(h, self.gpt.word_embeddings.weight,
                                     transpose_y=True)
        else:
            logits = self.lm_head(h)
        if labels is None:
            return logits
        loss = nn_ops.cross_entropy(
            manipulation.reshape(logits, (-1, self.cfg.vocab_size)),
            manipulation.reshape(labels, (-1,)))
        return loss

    def export_decode_params(self):
        """Weights as the stacked pytree the jitted decode programs
        consume (generate() and the serving engine): per-layer tensors
        stacked on a leading layer axis for lax.scan, plus embeddings
        and the (tied or separate) head. Values are concrete jax
        arrays snapshotted NOW — serving engines built from this see
        the weights as of this call."""
        import jax.numpy as jnp

        from ..core.lazy import concrete

        cfg = self.cfg

        def W(t):
            return concrete(t.value)

        stacked = {}
        per_layer = []
        for blk in self.gpt.blocks:
            per_layer.append({
                "ln1_w": W(blk.ln1.weight), "ln1_b": W(blk.ln1.bias),
                "qkv_w": W(blk.attn.qkv.weight),
                "qkv_b": W(blk.attn.qkv.bias),
                "out_w": W(blk.attn.out.weight),
                "out_b": W(blk.attn.out.bias),
                "ln2_w": W(blk.ln2.weight), "ln2_b": W(blk.ln2.bias),
                "fc1_w": W(blk.mlp.fc1.weight),
                "fc1_b": W(blk.mlp.fc1.bias),
                "fc2_w": W(blk.mlp.fc2.weight),
                "fc2_b": W(blk.mlp.fc2.bias)})
        for k in per_layer[0]:
            stacked[k] = jnp.stack([p[k] for p in per_layer])
        wemb = W(self.gpt.word_embeddings.weight)
        pemb = W(self.gpt.position_embeddings.weight)
        head = wemb.T if cfg.tie_embeddings else W(self.lm_head.weight)
        return {"stacked": stacked, "wemb": wemb, "pemb": pemb,
                "lnf_w": W(self.gpt.ln_f.weight),
                "lnf_b": W(self.gpt.ln_f.bias), "head": head}

    def build_serving_fns(self, num_slots, cache_len, sampling=False):
        """Slot-indexed cache programs for the continuous-batching
        engine (paddle_tpu.serving), over a pooled cache
        kc/vc [L, num_slots, nh, cache_len, hd]. Both programs thread
        the engine's rolling device state (toks/pos [S]) through, so
        consecutive steps chain entirely on device — the engine reads
        token values back only AFTER dispatching the next step, and
        the executables are built with kc/vc (and pos) donated so the
        pooled cache updates in place on donating backends:

          prefill(params, tokens [G, bucket], lengths [G], slots [G],
                  toks [S], pos [S], kc, vc)
              -> (first greedy tokens [G], toks', pos', kc, vc)
              ONE dispatch prefills a whole same-bucket admission
              group: the G claimed slot caches are gathered, the
              shared forward_t runs batched over the group, and the
              updated slices scatter back. The first tokens and next
              write positions also scatter into toks/pos so the next
              decode step consumes them with no host round-trip.
              Prompts are right-padded to the bucket (causal masking
              makes pad rows invisible to real rows, and decode's
              length mask hides their stale K/V afterwards);

          decode_step(params, toks [S], pos [S], kc, vc)
              -> (next greedy tokens [S], pos + 1, kc, vc)
              ONE fused program advancing every slot a token: per-slot
              K/V writes at each slot's own position, attention under
              the per-slot cache-length mask
              (ops.attention.cached_slot_attention). Positions come
              back incremented so decode chains into the next decode
              device-side.

        Both are pure and shape-stable; the engine AOT-compiles them
        (decode once, prefill once per (bucket, group size)).

        ``sampling=True`` threads per-slot sampling parameters
        (serving.sched.sampling — seeds/temps/top-k/top-p arrays)
        through both programs so temperature / top-k / top-p requests
        share the one compiled dispatch with greedy ones; the default
        keeps the original greedy-only signatures."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..ops import attention as attn_ops
        from ..serving.sched.sampling import build_sampling_head

        cfg = self.cfg
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh
        hidden = cfg.hidden_size
        ln, forward_t = _decode_forward_builder(nh, hd, hidden)
        head = build_sampling_head(cfg.vocab_size) if sampling else None

        def _prefill_core(params, tokens, lengths, slots, toks, pos,
                          kc, vc, samp):
            # tokens [G, bucket]; lengths/slots [G]; toks/pos [S]
            kcs = jnp.take(kc, slots, axis=1)   # [L, G, nh, C, hd]
            vcs = jnp.take(vc, slots, axis=1)
            logits, kcs, vcs = forward_t(params, tokens, jnp.int32(0),
                                         kcs, vcs)
            kc = kc.at[:, slots].set(kcs)
            vc = vc.at[:, slots].set(vcs)
            last = jnp.take_along_axis(
                logits, (lengths - 1)[:, None, None], axis=1)[:, 0]
            if samp is None:
                first = jnp.argmax(last, -1).astype(jnp.int32)  # [G]
            else:
                seeds, temps, topks, topps = samp
                first = head(last, seeds, lengths - 1, temps, topks,
                             topps)
            toks = toks.at[slots].set(first)
            # the next decode writes each group member at position
            # lengths[g] (its first generated token's cache row)
            pos = pos.at[slots].set(lengths)
            return first, toks, pos, kc, vc

        if sampling:
            def prefill(params, tokens, lengths, slots, toks, pos, kc,
                        vc, seeds, temps, topks, topps):
                return _prefill_core(params, tokens, lengths, slots,
                                     toks, pos, kc, vc,
                                     (seeds, temps, topks, topps))
        else:
            def prefill(params, tokens, lengths, slots, toks, pos, kc,
                        vc):
                return _prefill_core(params, tokens, lengths, slots,
                                     toks, pos, kc, vc, None)

        def write_slot(cache_l, new, pos):
            # cache_l [S, nh, C, hd], new [S, nh, hd]: each slot writes
            # its own row at its own position
            return jax.vmap(
                lambda c, n, p: lax.dynamic_update_slice(
                    c, n[:, None], (jnp.int32(0), p, jnp.int32(0))))(
                    cache_l, new, pos)

        def _decode_core(params, toks, pos, kc, vc, samp):
            S = toks.shape[0]
            # parked / idle slots' positions keep incrementing past
            # the table; clamp so the (ignored) row reads in-bounds
            x = params["wemb"][toks] + params["pemb"][
                jnp.minimum(pos, params["pemb"].shape[0] - 1)]

            def body(carry, inp):
                x = carry
                p, kcl, vcl = inp
                h_ = ln(x, p["ln1_w"], p["ln1_b"])
                qkv = h_ @ p["qkv_w"] + p["qkv_b"]
                qkv = qkv.reshape(S, 3, nh, hd).transpose(1, 0, 2, 3)
                q, k, v = qkv[0], qkv[1], qkv[2]      # [S, nh, hd]
                kcl = write_slot(kcl, k, pos)
                vcl = write_slot(vcl, v, pos)
                o = attn_ops.cached_slot_attention(q, kcl, vcl,
                                                   pos + 1)
                o = o.reshape(S, hidden)              # concat heads
                x = x + (o @ p["out_w"] + p["out_b"])
                h2 = ln(x, p["ln2_w"], p["ln2_b"])
                m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"],
                                approximate=True)
                return x + (m @ p["fc2_w"] + p["fc2_b"]), (kcl, vcl)

            x, (kc, vc) = lax.scan(body, x,
                                   (params["stacked"], kc, vc))
            logits = ln(x, params["lnf_w"], params["lnf_b"]) \
                @ params["head"]                      # [S, vocab]
            if samp is None:
                nxt = jnp.argmax(logits, -1).astype(jnp.int32)
            else:
                seeds, temps, topks, topps = samp
                nxt = head(logits, seeds, pos, temps, topks, topps)
            return nxt, pos + jnp.int32(1), kc, vc

        if sampling:
            def decode_step(params, toks, pos, kc, vc, seeds, temps,
                            topks, topps):
                return _decode_core(params, toks, pos, kc, vc,
                                    (seeds, temps, topks, topps))
        else:
            def decode_step(params, toks, pos, kc, vc):
                return _decode_core(params, toks, pos, kc, vc, None)

        return prefill, decode_step

    def build_paged_serving_fns(self, num_slots, block_size, num_blocks,
                                blocks_per_slot, sampling=False,
                                attn_kernel=False):
        """Paged-cache analogues of build_serving_fns for the
        block-granular KV pool (serving.paged): same decode math via
        the shared _decode_forward_builder, cache addressed through a
        fixed-shape block table so shared-prefix blocks are reused
        instead of re-prefilled —

          paged_prefill(params, tokens [1, B], tail_len, start, slot,
                        final, bt_row [MB], toks [S], pos [S], kc, vc)
              -> (first [1], toks', pos', kc, vc)
          paged_decode(params, toks [S], pos [S], tables [S, MB],
                       kc, vc)
              -> (next [S], pos + 1, kc, vc)

        with kc/vc [L, num_blocks, nh, block_size, hd]. Both are pure
        and shape-stable (start/tail_len/final are traced scalars, so
        prefix AND chunk variety costs zero compiles); the engine
        AOT-compiles them (decode once, prefill once per tail bucket).
        ``sampling=True`` appends per-slot sampling parameters to both
        signatures (serving.sched.sampling); ``attn_kernel=True``
        swaps the decode attention for the Pallas paged kernel
        (ops.paged_attention) without changing either signature."""
        from ..serving.paged.programs import build_paged_fns
        return build_paged_fns(self.cfg, num_slots, block_size,
                               num_blocks, blocks_per_slot,
                               sampling=sampling,
                               attn_kernel=attn_kernel)

    def build_spec_verify_fn(self, num_slots, cache_len, spec_k):
        """The speculative k-token verify program over the
        slot-contiguous pool (serving.spec.programs): one fixed-shape
        ``[S, k+1]``-position dispatch verifying each slot's k drafted
        continuations against the model's own greedy choices —
        longest-accepted-prefix on device, bit-exact with plain
        decode by construction (ServingConfig(speculative=True))."""
        from ..serving.spec.programs import build_spec_verify_fn
        return build_spec_verify_fn(self.cfg, num_slots, cache_len,
                                    spec_k)

    def build_paged_spec_verify_fn(self, num_slots, block_size,
                                   num_blocks, blocks_per_slot,
                                   spec_k):
        """Paged-pool analogue of build_spec_verify_fn: candidate K/V
        rows scatter straight into each slot's privately-owned blocks
        under PR 7's whole-position clamp (overflow rows trash-routed),
        attention through the gathered block-table view."""
        from ..serving.spec.programs import build_paged_spec_verify_fn
        return build_paged_spec_verify_fn(
            self.cfg, num_slots, block_size, num_blocks,
            blocks_per_slot, spec_k)

    def build_chunk_prefill_fn(self, cache_len, sampling=False):
        """The chunked-prefill program over the slot-contiguous pool
        (serving.sched.programs.build_chunk_fns): one fixed-width
        ``[1, chunk]`` dispatch per chunk with traced start / length /
        slot / final scalars, so ANY prompt-length mix reuses one
        compiled program per chunk width — the program that lets a
        long prompt interleave with decode steps instead of stalling
        them (ServingConfig(prefill_chunk=...))."""
        from ..serving.sched.programs import build_chunk_fns
        return build_chunk_fns(self.cfg, cache_len, sampling=sampling)

    _DECODE_CACHE_MAX = 16

    @staticmethod
    def _decode_cache_get(cache, key, build):
        """LRU get-or-jit on the per-shape decode cache: each distinct
        call signature compiles its own executable, and serving loops
        with arbitrary prompt lengths must not retain unboundedly
        many."""
        import jax
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = jax.jit(build)
            while len(cache) > GPTForCausalLM._DECODE_CACHE_MAX:
                cache.popitem(last=False)
        else:
            cache.move_to_end(key)
        return fn

    def generate(self, input_ids, max_new_tokens=32, temperature=1.0,
                 top_k=0, seed=0, num_beams=1):
        """TPU-native autoregressive decoding: prefill + per-token
        steps run as ONE jitted program — a `lax.scan` over positions
        with a static-shape KV cache ([L, b, heads, total, hd], write
        index advances; no dynamic shapes anywhere, so XLA compiles a
        single decode executable). Greedy when temperature<=0 or
        top_k==1; otherwise temperature sampling over the top_k logits
        (0 = full vocab). Reference analogue: the generation utilities
        the fluid-era GPT examples build per-step in Python — here the
        whole decode is compiler-scheduled.

        Works for TP-configured models too: parameters are FULL logical
        arrays (GSPMD shards activations inside the pjit'd train step,
        not the stored weights), so decode reads them directly and runs
        as a single-device program — correct for any model whose
        weights + caches fit one chip. Sharding the decode itself over
        the mesh (for models that NEED TP at inference) would add
        in_shardings over the head axis; not done here."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from ..core.lazy import concrete
        from ..core.tensor import Tensor

        cfg = self.cfg
        nh = cfg.num_heads
        hd = cfg.hidden_size // nh

        params = self.export_decode_params()
        ids = jnp.asarray(
            concrete(getattr(input_ids, "value", input_ids)), jnp.int32)
        b, s0 = ids.shape
        n_new = int(max_new_tokens)
        total = s0 + n_new
        if total > cfg.max_seq_len:
            raise ValueError(f"prompt {s0} + max_new_tokens "
                             f"{max_new_tokens} exceeds max_seq_len "
                             f"{cfg.max_seq_len}")
        if n_new <= 0:
            return Tensor(ids.astype(jnp.int64))
        L = cfg.num_layers
        greedy = temperature <= 0 or top_k == 1
        kk = min(int(top_k), cfg.vocab_size)  # top_k > vocab = full vocab

        # decode math shared with the serving engine — ONE definition
        # (parity between generate() and continuous batching holds by
        # construction, not by testing alone)
        _, forward_t = _decode_forward_builder(nh, hd, cfg.hidden_size)

        def pick(logits, key, temp):
            # logits [b, vocab]
            if greedy:
                return jnp.argmax(logits, -1).astype(jnp.int32)
            lg = logits / temp
            if kk > 0:
                kth = lax.top_k(lg, kk)[0][:, -1:]
                lg = jnp.where(lg < kth, jnp.float32(-1e30), lg)
            return jax.random.categorical(key, lg).astype(jnp.int32)

        def decode(pr, ids, key, temp):
            kc = jnp.zeros((L, b, nh, total, hd), jnp.float32)
            vc = jnp.zeros_like(kc)
            logits, kc, vc = forward_t(pr, ids, jnp.int32(0), kc, vc)
            key, sub = jax.random.split(key)
            first = pick(logits[:, -1], sub, temp)
            if n_new == 1:
                return jnp.concatenate([ids, first[:, None]], axis=1)

            def step(carry, _):
                tok, pos, kc, vc, key = carry
                logits, kc, vc = forward_t(pr, tok[:, None], pos, kc, vc)
                key, sub = jax.random.split(key)
                nxt = pick(logits[:, -1], sub, temp)
                return (nxt, pos + 1, kc, vc, key), nxt

            # n_new - 1 steps: the prefill already produced token 1
            _, rest = lax.scan(step, (first, jnp.int32(s0), kc, vc, key),
                               None, length=n_new - 1)
            gen = jnp.concatenate([first[:, None], rest.T], axis=1)
            return jnp.concatenate([ids, gen], axis=1)

        K = int(num_beams)

        def beam_decode(pr, ids):
            # deterministic beam search over cumulative log-prob
            # (reference analogue: fluid beam_search op + gather_tree —
            # here the whole search is one scanned program; beams are a
            # batch*K batch dim, caches re-gathered by beam each step)
            kc = jnp.zeros((L, b, nh, total, hd), jnp.float32)
            vc = jnp.zeros_like(kc)
            logits, kc, vc = forward_t(pr, ids, jnp.int32(0), kc, vc)
            lp0 = jax.nn.log_softmax(logits[:, -1])        # [b, V]
            scores, tok = lax.top_k(lp0, K)                # [b, K]
            tok = tok.astype(jnp.int32)
            kc = jnp.repeat(kc, K, axis=1)                 # beams join batch
            vc = jnp.repeat(vc, K, axis=1)
            seqs = jnp.zeros((b, K, n_new), jnp.int32)
            z = jnp.int32(0)
            seqs = lax.dynamic_update_slice(seqs, tok[:, :, None],
                                            (z, z, z))

            def step(carry, i):
                seqs, scores, tok, pos, kc, vc = carry
                logits, kc, vc = forward_t(pr, tok.reshape(b * K, 1),
                                           pos, kc, vc)
                V = logits.shape[-1]
                lp = jax.nn.log_softmax(logits[:, -1]).reshape(b, K, V)
                cand = scores[:, :, None] + lp
                scores, flat = lax.top_k(cand.reshape(b, K * V), K)
                beam = (flat // V).astype(jnp.int32)
                tok = (flat % V).astype(jnp.int32)
                kc = kc.reshape(L, b, K, nh, total, hd)
                vc = vc.reshape(L, b, K, nh, total, hd)
                idx = beam[None, :, :, None, None, None]
                kc = jnp.take_along_axis(kc, idx, axis=2) \
                    .reshape(L, b * K, nh, total, hd)
                vc = jnp.take_along_axis(vc, idx, axis=2) \
                    .reshape(L, b * K, nh, total, hd)
                seqs = jnp.take_along_axis(seqs, beam[:, :, None],
                                           axis=1)
                seqs = lax.dynamic_update_slice(
                    seqs, tok[:, :, None], (z, z, i))
                return (seqs, scores, tok, pos + jnp.int32(1),
                        kc, vc), None

            if n_new > 1:
                (seqs, scores, _, _, _, _), _ = lax.scan(
                    step, (seqs, scores, tok, jnp.int32(s0), kc, vc),
                    jnp.arange(1, n_new, dtype=jnp.int32))
            # top_k keeps beams sorted by score: beam 0 is the best
            return jnp.concatenate([ids, seqs[:, 0]], axis=1)

        # cache the jitted decode per call signature; weights arrive as
        # ARGUMENTS (not closure constants), so repeat calls — and
        # calls after further training — reuse the same executable.
        # Every distinct (batch, prompt_len, max_new_tokens) compiles
        # its own executable; an LRU cap keeps variable-length serving
        # loops from retaining unboundedly many (callers who want zero
        # recompiles should pad prompts to a fixed length themselves,
        # since padding here would let attention see the pad tokens).
        import collections
        cache = self.__dict__.setdefault("_decode_jit",
                                         collections.OrderedDict())
        if K < 1:
            raise ValueError(f"num_beams must be >= 1, got {num_beams}")
        if K > 1:
            if K > cfg.vocab_size:
                raise ValueError(f"num_beams {K} > vocab size "
                                 f"{cfg.vocab_size}")
            if temperature not in (1.0, 0.0) or top_k or seed:
                # beam search here is pure max-log-prob search; honoring
                # sampling args would be a different algorithm — reject
                # rather than silently ignore them
                raise ValueError(
                    "num_beams > 1 is deterministic beam search; "
                    "temperature/top_k/seed do not apply (use "
                    "num_beams=1 for sampling)")
            ck = ("beam", b, s0, n_new, K)
            fn = self._decode_cache_get(cache, ck, beam_decode)
            out = fn(params, ids)
        else:
            ck = (b, s0, n_new, greedy, kk)
            fn = self._decode_cache_get(cache, ck, decode)
            out = fn(params, ids, jax.random.PRNGKey(int(seed)),
                     jnp.float32(max(temperature, 1e-6)))
        return Tensor(out.astype(jnp.int64))

    def pp_segments(self):
        """Pipeline-parallel segmentation (see PipelineParallel): edge
        segments run GSPMD on the full mesh — which makes the tied
        embedding (used in pre AND post) trivially shared — and the
        transformer blocks are the pipelined homogeneous run."""
        core = self.gpt

        def pre(input_ids):
            s = input_ids.shape[1]
            pos = creation.arange(0, s, dtype="int64")
            x = core.word_embeddings(input_ids)
            x = math_ops.add(x, core.position_embeddings(pos))
            if core.cfg.dropout:
                x = nn_ops.dropout(x, p=core.cfg.dropout,
                                   training=core.training)
            return x

        def post(h, labels=None):
            h = core.ln_f(h)
            return self._head_loss(h, labels)

        return {"pre": pre, "blocks": list(core.blocks), "post": post}


class BertModel(_TransformerCore):
    """Encoder core (BERT style: post-norm, token types)."""

    def __init__(self, cfg):
        super().__init__(cfg, causal=False, pre_norm=False,
                         with_token_type=True)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attn_mask=None):
        h = super().forward(input_ids, token_type_ids, attn_mask)
        pooled = nn_ops.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForPretraining(nn.Layer):
    """MLM + NSP heads (reference pretraining objective for config 3)."""

    def __init__(self, cfg):
        super().__init__()
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.mlm_transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.mlm_ln = nn.LayerNorm(cfg.hidden_size)
        self.nsp_head = nn.Linear(cfg.hidden_size, 2)

    def forward(self, input_ids, token_type_ids=None, masked_lm_labels=None,
                next_sentence_labels=None):
        h, pooled = self.bert(input_ids, token_type_ids)
        t = nn_ops.gelu(self.mlm_transform(h), approximate=True)
        t = self.mlm_ln(t)
        logits = math_ops.matmul(t, self.bert.word_embeddings.weight,
                                 transpose_y=True)
        if masked_lm_labels is None:
            return logits
        mlm_loss = nn_ops.cross_entropy(
            manipulation.reshape(logits, (-1, self.cfg.vocab_size)),
            manipulation.reshape(masked_lm_labels, (-1,)),
            ignore_index=-1)
        if next_sentence_labels is not None:
            nsp_logits = self.nsp_head(pooled)
            nsp_loss = nn_ops.cross_entropy(
                nsp_logits, manipulation.reshape(next_sentence_labels, (-1,)))
            return math_ops.add(mlm_loss, nsp_loss)
        return mlm_loss


def bert_base(vocab_size=30522, max_seq_len=512, **kwargs):
    cfg = TransformerLMConfig(vocab_size=vocab_size, hidden_size=768,
                              num_layers=12, num_heads=12,
                              max_seq_len=max_seq_len, **kwargs)
    return BertForPretraining(cfg)


def gpt3_1p3b(vocab_size=50304, max_seq_len=1024, **kwargs):
    """GPT-3 1.3B: 24 layers, hidden 2048, 16 heads (BASELINE config 5)."""
    cfg = TransformerLMConfig(vocab_size=vocab_size, hidden_size=2048,
                              num_layers=24, num_heads=16,
                              max_seq_len=max_seq_len, **kwargs)
    return GPTForCausalLM(cfg)
