"""paddle.text equivalent + transformer model zoo (BERT / GPT).

The reference ships text datasets (python/paddle/text/datasets/) and the
ERNIE/GPT model definitions live in external repos; here the flagship
transformer models are first-class since they anchor the perf baselines
(BASELINE.md configs 3 and 5).
"""
from .models import (  # noqa: F401
    BertModel, BertForPretraining, GPTModel, GPTForCausalLM, gpt3_1p3b,
    bert_base, TransformerLMConfig,
)
from . import datasets  # noqa: F401
from .datasets import (  # noqa: F401
    Conll05st, Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
)
