"""paddle.hub equivalent (reference: python/paddle/hub.py — list/help/load
entrypoints discovered from a repo's hubconf.py).

Zero-egress design: sources are local directories (containing hubconf.py)
or importable module paths (e.g. "paddle_tpu.vision.models"); the
reference's github/gitee download path is gated off with a clear error.
"""
import importlib
import importlib.util
import os
import sys


def _load_hubconf(repo_dir):
    path = os.path.join(repo_dir, "hubconf.py")
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no hubconf.py in {repo_dir}")
    spec = importlib.util.spec_from_file_location("hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["hubconf"] = mod
    spec.loader.exec_module(mod)
    return mod


def _resolve(repo_dir, source):
    if source in ("github", "gitee"):
        raise RuntimeError(
            "remote hub sources are unavailable in this environment; "
            "use source='local' with a directory containing hubconf.py, "
            "or an importable module path")
    if os.path.isdir(repo_dir):
        return _load_hubconf(repo_dir)
    return importlib.import_module(repo_dir)


def _entrypoints(mod):
    return {name: fn for name, fn in vars(mod).items()
            if callable(fn) and not name.startswith("_")
            and not isinstance(fn, type)}


def list(repo_dir, source="local", force_reload=False):  # noqa: A001
    """Names of callable model entrypoints exposed by the repo."""
    return sorted(_entrypoints(_resolve(repo_dir, source)))


def help(repo_dir, model, source="local", force_reload=False):  # noqa: A001
    fns = _entrypoints(_resolve(repo_dir, source))
    if model not in fns:
        raise ValueError(f"unknown model {model!r}; have {sorted(fns)}")
    return fns[model].__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Instantiate `model` from the repo's entrypoints."""
    fns = _entrypoints(_resolve(repo_dir, source))
    if model not in fns:
        raise ValueError(f"unknown model {model!r}; have {sorted(fns)}")
    return fns[model](**kwargs)
