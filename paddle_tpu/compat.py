"""String/number compat helpers (reference: python/paddle/compat.py —
py2/py3-era text conversion utilities still used by dataset/fleet
plumbing and user code).
"""
import math

__all__ = []

int_type = int
long_type = int


def _convert(obj, fn, inplace):
    if obj is None:
        return obj
    if isinstance(obj, (list, set)):
        converted = [_convert(item, fn, inplace) for item in obj]
        if inplace:
            obj.clear()
            if isinstance(obj, list):
                obj.extend(converted)
            else:
                obj.update(converted)
            return obj
        return type(obj)(converted)
    return fn(obj)


def to_text(obj, encoding="utf-8", inplace=False):
    """bytes -> str (elementwise through list/set containers);
    reference compat.py:25."""
    def one(o):
        if isinstance(o, bytes):
            return o.decode(encoding)
        return str(o) if not isinstance(o, str) else o
    return _convert(obj, one, inplace)


def to_bytes(obj, encoding="utf-8", inplace=False):
    """str -> bytes (elementwise through list/set containers);
    reference compat.py:121."""
    def one(o):
        if isinstance(o, str):
            return o.encode(encoding)
        return bytes(o) if not isinstance(o, bytes) else o
    return _convert(obj, one, inplace)


def round(x, d=0):  # noqa: A001
    """Half-away-from-zero rounding (python2 semantics the reference
    preserves; python3's builtin rounds half-to-even);
    reference compat.py:206."""
    p = 10 ** d
    if x > 0:
        return float(math.floor((x * p) + math.copysign(0.5, x))) / p
    if x < 0:
        return float(math.ceil((x * p) + math.copysign(0.5, x))) / p
    return math.copysign(0.0, x)


def floor_division(x, y):
    """reference compat.py:232."""
    return x // y


def get_exception_message(exc):
    """reference compat.py:249."""
    return str(exc)
