"""AMP autocast.

TPU-native equivalent of the reference AMP system (reference:
paddle/fluid/imperative/amp_auto_cast.h:31 AmpOperators white/black lists,
:85 AutoCastInputs; python/paddle/amp/auto_cast.py:20). On TPU the natural
low-precision dtype is bfloat16 (no loss scaling strictly required, but
GradScaler is provided for float16 parity). The cast is applied inside the
op's jitted closure so it fuses with the op (core/dispatch.py).

O1: ops on the white list run in low precision; black list stays fp32;
gray (everything else) runs in input dtype. O2: everything except the
black list runs in low precision.
"""
import threading
from contextlib import contextmanager

import jax.numpy as jnp

_state = threading.local()

# Reference white list (matmul-heavy ops benefit from MXU low precision):
# imperative/amp_auto_cast.cc default lists.
WHITE_LIST = {
    "matmul", "matmul_v2", "mul", "conv2d", "conv3d", "conv2d_transpose",
    "einsum", "bmm", "addmm", "attention", "flash_attention",
    # the fused linear op IS a matmul (reference white list has mul/fc);
    # without it every nn.Linear ran fp32 under O1
    "linear",
    # the fused LM head accumulates in f32 internally; bf16 inputs keep
    # its vocab matmul on the bf16 MXU
    "fused_linear_cross_entropy",
}
# Ops numerically unsafe in low precision.
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "pow", "square", "sqrt", "rsqrt",
    "softmax_with_cross_entropy", "cross_entropy", "log_softmax",
    "mean", "sum", "reduce_mean", "reduce_sum", "norm", "cos_sim",
    "layer_norm", "batch_norm", "softmax", "erf", "cumsum",
}


def _amp_state():
    return getattr(_state, "amp", None)


def amp_enabled():
    return _amp_state() is not None


def _cast_dtype_for(op_name):
    """Called by the dispatcher: dtype to cast float inputs to, or None."""
    st = _amp_state()
    if st is None:
        return None
    level, dtype, custom_white, custom_black = st
    if op_name in custom_black or op_name in BLACK_LIST:
        return None
    if level == "O2":
        return dtype
    if op_name in custom_white or op_name in WHITE_LIST:
        return dtype
    return None


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    """paddle.amp.auto_cast equivalent."""
    if level not in ("O0", "O1", "O2"):
        raise ValueError(f"level must be O0/O1/O2, got {level}")
    jdt = {"bfloat16": jnp.bfloat16, "float16": jnp.float16}[dtype]
    prev = _amp_state()
    if enable and level != "O0":
        _state.amp = (level, jdt,
                      frozenset(custom_white_list or ()),
                      frozenset(custom_black_list or ()))
    else:
        _state.amp = None
    try:
        yield
    finally:
        _state.amp = prev


amp_guard = auto_cast
