from . import auto_cast  # noqa: F401  (module; dispatch imports it)
from .auto_cast import auto_cast, amp_guard, amp_enabled  # noqa: F811,F401
from .grad_scaler import GradScaler, AmpScaler  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "GradScaler", "AmpScaler", "decorate"]


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate: for O2, cast model params to low precision.

    Reference: python/paddle/amp/auto_cast.py amp_decorate. With bfloat16 on
    TPU master weights default to keeping fp32 copies in the optimizer.
    """
    import jax.numpy as jnp
    from ..core.tensor import Tensor
    if level == "O2":
        jdt = {"bfloat16": jnp.bfloat16, "float16": jnp.float16}[dtype]
        model_list = models if isinstance(models, (list, tuple)) else [models]
        for m in model_list:
            for p in m.parameters():
                p.value = p.value.astype(jdt)
    if optimizers is None:
        return models
    return models, optimizers
