"""Dynamic loss scaling.

TPU-native equivalent of the reference GradScaler (reference:
python/paddle/amp/grad_scaler.py:20, built on
paddle/fluid/operators/amp/check_finite_and_unscale_op and
update_loss_scaling_op). The two AMP primitive ops are implemented as pure
jax functions; the scale/good-steps counters are state Tensors so a traced
training step threads them functionally.

With bfloat16 (TPU default) loss scaling is unnecessary; enable=True is
mainly for float16 parity and numerics experiments.
"""
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


@register_op("check_finite_and_unscale", differentiable=False)
def _check_finite_and_unscale(*args):
    """Last arg is scale; rest are grads. Returns unscaled grads + found_inf.
    Reference: operators/amp/check_finite_and_unscale_op.h."""
    grads, scale = args[:-1], args[-1]
    inv = 1.0 / scale
    found = jnp.zeros((), jnp.bool_)
    outs = []
    for g in grads:
        outs.append(g * inv.astype(g.dtype))
        found = found | ~jnp.all(jnp.isfinite(g))
    return tuple(outs) + (found,)


@register_op("update_loss_scaling", differentiable=False)
def _update_loss_scaling(scale, good_steps, bad_steps, found_inf, *,
                         incr_every_n_steps, decr_every_n_nan_or_inf,
                         incr_ratio, decr_ratio):
    """Reference: operators/amp/update_loss_scaling_op.h — grow after N
    consecutive good steps, shrink after decr_every_n_nan_or_inf
    consecutive bad steps. Branch-free so it traces."""
    new_bad = jnp.where(found_inf, bad_steps + 1, 0)
    new_good = jnp.where(found_inf, 0, good_steps + 1)
    shrink = new_bad >= decr_every_n_nan_or_inf
    grow = new_good >= incr_every_n_steps
    new_scale = jnp.where(shrink, jnp.maximum(scale * decr_ratio, 1.0),
                          jnp.where(grow, scale * incr_ratio, scale))
    new_scale = jnp.where(jnp.isfinite(new_scale), new_scale, scale)
    new_bad = jnp.where(shrink, 0, new_bad)
    new_good = jnp.where(grow, 0, new_good)
    return new_scale, new_good, new_bad


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5,
                 incr_every_n_steps=1000, decr_every_n_nan_or_inf=1,
                 use_dynamic_loss_scaling=True):
        self._enable = enable
        self._use_dynamic = use_dynamic_loss_scaling
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n = int(decr_every_n_nan_or_inf)
        self._scale = Tensor(jnp.asarray(float(init_loss_scaling), jnp.float32),
                             name="loss_scaling", persistable=True)
        self._good_steps = Tensor(jnp.asarray(0, jnp.int32),
                                  name="loss_scaling_good_steps",
                                  persistable=True)
        self._bad_steps = Tensor(jnp.asarray(0, jnp.int32),
                                 name="loss_scaling_bad_steps",
                                 persistable=True)
        self._found_inf_t = None

    def is_enable(self):
        return self._enable

    def scale(self, loss):
        if not self._enable:
            return loss
        from .. import ops
        return ops.math.multiply(loss, ops.math.cast(
            Tensor(self._scale.value), dtype=loss.value.dtype))

    def unscale_(self, optimizer):
        if not self._enable:
            return
        params = [p for p in optimizer._parameter_list()
                  if p._grad is not None]
        if not params:
            return
        grads = [p._grad for p in params]
        outs = _check_finite_and_unscale(*grads, self._scale)
        new_grads, found = outs[:-1], outs[-1]
        for p, g in zip(params, new_grads):
            p._grad.value = g.value
        self._found_inf_t = found

    def step(self, optimizer):
        """scaler.step(opt): unscale then apply the update, masked on
        overflow. Branch-free (no python conditional on the device value):
        grads are zeroed and every mutated state tensor is restored with
        where(found_inf, old, new), so skipped-update semantics hold in
        both eager and traced (to_static) execution — and optimizer state
        is always materialized, keeping trace capture complete."""
        if not self._enable:
            optimizer.step()
            return
        if self._found_inf_t is None:
            self.unscale_(optimizer)
        found = self._found_inf_t
        if found is None:
            optimizer.step()
            self.update()
            return
        fv = found.value
        params = [p for p in optimizer._parameter_list()
                  if p._grad is not None and p.trainable]
        snapshot = [(p, p.value) for p in params]
        for store in optimizer._accumulators.values():
            for t in store.values():
                snapshot.append((t, t.value))
        for p in params:
            g = p._grad.value
            p._grad.value = jnp.where(fv, jnp.zeros_like(g), g)
        optimizer.step()
        for t, old in snapshot:
            t.value = jnp.where(fv, old, t.value)
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._use_dynamic):
            self._found_inf_t = None
            return
        found = getattr(self, "_found_inf_t", None)
        if found is None:
            return
        new_scale, new_good, new_bad = _update_loss_scaling(
            self._scale, self._good_steps, self._bad_steps, found,
            incr_every_n_steps=self._incr_every_n_steps,
            decr_every_n_nan_or_inf=self._decr_every_n,
            incr_ratio=self._incr_ratio, decr_ratio=self._decr_ratio)
        self._scale.value = new_scale.value
        self._good_steps.value = new_good.value
        self._bad_steps.value = new_bad.value
        self._found_inf_t = None

    def state_dict(self):
        return {"scale": self._scale.numpy(),
                "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every_n_steps,
                "decr_every_n_nan_or_inf": self._decr_every_n,
                "good_steps": self._good_steps.numpy(),
                "use_dynamic_loss_scaling": self._use_dynamic}

    def load_state_dict(self, state):
        import jax.numpy as jnp
        self._scale.value = jnp.asarray(state["scale"], jnp.float32)
        self._good_steps.value = jnp.asarray(state["good_steps"], jnp.int32)

    def get_loss_scaling(self):
        return Tensor(self._scale.value)


def _is_tracer(v):
    import jax.core
    return isinstance(v, jax.core.Tracer)


AmpScaler = GradScaler
