"""paddle.regularizer equivalent.

Reference parity: python/paddle/regularizer.py (L1Decay:20, L2Decay:82)
over fluid/regularizer.py. The optimizer consumes these through its
weight_decay argument: L2 adds coeff*param to the gradient, L1 adds
coeff*sign(param) — matching the reference's append_regularization_ops.
"""


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self._mode = "l1"

    def __repr__(self):
        return f"L1Decay(coeff={self._coeff})"


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)
        self._mode = "l2"

    def __repr__(self):
        return f"L2Decay(coeff={self._coeff})"
