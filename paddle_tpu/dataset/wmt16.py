"""Reference: dataset/wmt16.py — train/test/validation(src_dict_size,
trg_dict_size, src_lang) reader creators + get_dict."""
import numpy as np

__all__ = []


def _reader(mode, src_dict_size, trg_dict_size, src_lang):
    from ..text.datasets import WMT16
    ds = WMT16(mode=mode, src_dict_size=src_dict_size,
               trg_dict_size=trg_dict_size, lang=src_lang)

    def reader():
        for sample in ds:
            yield tuple(list(np.asarray(f).reshape(-1)) for f in sample)

    return reader


def train(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("train", src_dict_size, trg_dict_size, src_lang)


def test(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("test", src_dict_size, trg_dict_size, src_lang)


def validation(src_dict_size, trg_dict_size, src_lang="en"):
    return _reader("val", src_dict_size, trg_dict_size, src_lang)


def get_dict(lang, dict_size, reverse=False):
    from ..text.datasets import WMT16
    ds = WMT16(mode="train", src_dict_size=dict_size,
               trg_dict_size=dict_size)
    return ds.get_dict(lang, reverse=reverse)


def fetch():
    pass
