"""Reference: dataset/image.py — HWC numpy image utilities (the
reference shells out to cv2; these are pure-numpy equivalents, with
PIL used only for file decoding when available)."""
import numpy as np

__all__ = []


def load_image_bytes(data, is_color=True):
    import io

    from PIL import Image
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(path, is_color=True):
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def _resize(im, h, w):
    """Nearest-neighbor resize (HWC or HW)."""
    src_h, src_w = im.shape[:2]
    rows = (np.arange(h) * (src_h / h)).astype(int).clip(0, src_h - 1)
    cols = (np.arange(w) * (src_w / w)).astype(int).clip(0, src_w - 1)
    return im[rows][:, cols]


def resize_short(im, size):
    """Scale so the SHORT side equals `size` (reference :193)."""
    h, w = im.shape[:2]
    if h < w:
        return _resize(im, size, int(round(w * size / h)))
    return _resize(im, int(round(h * size / w)), size)


def to_chw(im, order=(2, 0, 1)):
    return im.transpose(order)


def center_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = max((h - size) // 2, 0)
    w0 = max((w - size) // 2, 0)
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im, size, is_color=True):
    h, w = im.shape[:2]
    h0 = np.random.randint(0, max(h - size, 0) + 1)
    w0 = np.random.randint(0, max(w - size, 0) + 1)
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im, is_color=True):
    return im[:, ::-1]


def simple_transform(im, resize_size, crop_size, is_train,
                     is_color=True, mean=None):
    """Reference :323 — resize-short, crop (random+flip when training,
    center otherwise), CHW, optional mean subtraction."""
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color)
        if np.random.randint(2):
            im = left_right_flip(im, is_color)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype("float32")
    if mean is not None:
        mean = np.asarray(mean, "float32")
        im = im - (mean if mean.ndim != 1 else mean[:, None, None])
    return im


def load_and_transform(filename, resize_size, crop_size, is_train,
                       is_color=True, mean=None):
    return simple_transform(load_image(filename, is_color), resize_size,
                            crop_size, is_train, is_color, mean)
