"""Legacy pre-2.0 dataset package (reference: python/paddle/dataset/ —
reader-creator API deprecated in favor of paddle.io + the class-based
vision/text datasets, but still shipped and imported by fluid-era
code).

Each module exposes the reference reader-creator surface (train/test
return a callable yielding sample tuples) delegating to the modern
Dataset classes, which read local DATA_HOME files and fall back to
deterministic synthetic data in offline environments.
"""
from . import (cifar, common, conll05, flowers, image, imdb, imikolov,  # noqa: F401
               mnist, movielens, uci_housing, voc2012, wmt14, wmt16)

__all__ = []
