"""Reference: dataset/imdb.py — word_dict() + train/test(word_idx)
reader creators yielding (word-id sequence, 0/1 label)."""
import numpy as np

__all__ = []


def word_dict():
    from ..text.datasets import Imdb
    return dict(Imdb(mode="train").word_idx)


def _reader(mode, word_idx):
    from ..text.datasets import Imdb
    ds = Imdb(mode=mode)  # once per creator
    # the caller sizes their embedding table by THEIR dict: keep every
    # yielded id a valid index into it
    n_vocab = max(1, len(word_idx)) if word_idx else None

    def reader():
        for doc, label in ds:
            ids = [int(i) for i in np.asarray(doc).reshape(-1)]
            if n_vocab is not None:
                ids = [i % n_vocab for i in ids]
            yield ids, int(np.asarray(label).reshape(-1)[0])

    return reader


def train(word_idx):
    return _reader("train", word_idx)


def test(word_idx):
    return _reader("test", word_idx)


def fetch():
    pass
