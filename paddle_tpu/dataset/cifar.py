"""Reference: dataset/cifar.py — train10/test10/train100/test100 reader
creators yielding (flat-3072 float32 image in [0, 1], int label)."""
import numpy as np

__all__ = []


def _reader(cls_name, mode, cycle=False):
    from ..vision import datasets as vds
    ds = getattr(vds, cls_name)(mode=mode)  # once per creator

    def reader():
        while True:
            for img, label in ds:
                flat = np.asarray(img, "float32").reshape(-1)
                yield flat, int(np.asarray(label).reshape(-1)[0])
            if not cycle:
                break

    return reader


def train10(cycle=False):
    return _reader("Cifar10", "train", cycle)


def test10(cycle=False):
    return _reader("Cifar10", "test", cycle)


def train100():
    return _reader("Cifar100", "train")


def test100():
    return _reader("Cifar100", "test")


def fetch():
    pass
