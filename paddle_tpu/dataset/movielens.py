"""Reference: dataset/movielens.py — train/test readers + metadata
queries over the MovieLens 1M schema. Sample layout (modern Movielens
class): (user_id, gender, age, job, movie_id, title_ids, categories,
rating)."""
import collections

import numpy as np

__all__ = []

MovieInfo = collections.namedtuple("MovieInfo",
                                   ["index", "categories", "title"])
UserInfo = collections.namedtuple("UserInfo",
                                  ["index", "gender", "age", "job"])


_ds_cache = {}


def _ds(mode="train"):
    # metadata queries (max_*_id, movie_info, ...) are typically all
    # called during one model build — cache per mode like the
    # reference's __initialize_meta_info__ module global
    ds = _ds_cache.get(mode)
    if ds is None:
        from ..text.datasets import Movielens
        ds = _ds_cache[mode] = Movielens(mode=mode)
    return ds


def _reader(mode):
    def reader():
        for sample in _ds(mode):
            yield tuple(np.asarray(f).reshape(-1) for f in sample)

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def _field_max(idx):
    return max(int(np.asarray(s[idx]).reshape(-1)[0])
               for s in _ds("train"))


def max_movie_id():
    return _field_max(4)


def max_user_id():
    return _field_max(0)


def max_job_id():
    return _field_max(3)


def get_movie_title_dict():
    """word -> index over every title word id in the data."""
    ids = set()
    for s in _ds("train"):
        ids.update(int(i) for i in np.asarray(s[5]).reshape(-1))
    return {f"w{i}": n for n, i in enumerate(sorted(ids))}


def movie_categories():
    """category name -> index over every category id in the data."""
    ids = set()
    for s in _ds("train"):
        ids.update(int(i) for i in np.asarray(s[6]).reshape(-1))
    return {f"c{i}": n for n, i in enumerate(sorted(ids))}


def user_info():
    """user id -> UserInfo."""
    out = {}
    for s in _ds("train"):
        uid = int(np.asarray(s[0]).reshape(-1)[0])
        out[uid] = UserInfo(index=uid,
                            gender=int(np.asarray(s[1]).reshape(-1)[0]),
                            age=int(np.asarray(s[2]).reshape(-1)[0]),
                            job=int(np.asarray(s[3]).reshape(-1)[0]))
    return out


def movie_info():
    """movie id -> MovieInfo."""
    out = {}
    for s in _ds("train"):
        mid = int(np.asarray(s[4]).reshape(-1)[0])
        out[mid] = MovieInfo(
            index=mid,
            categories=[int(i) for i in np.asarray(s[6]).reshape(-1)],
            title=[int(i) for i in np.asarray(s[5]).reshape(-1)])
    return out


def fetch():
    pass
