"""Reference: dataset/voc2012.py — train/test/val readers yielding
(image, segmentation label) arrays."""
import numpy as np

__all__ = []


def _reader(mode):
    from ..vision.datasets import VOC2012
    ds = VOC2012(mode=mode)  # once per creator

    def reader():
        for img, label in ds:
            yield np.asarray(img), np.asarray(label)

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def val():
    return _reader("valid")


def fetch():
    pass
