"""Reference: dataset/mnist.py — reader creators yielding
(flat-784 float32 image scaled to [-1, 1], int label)."""
import numpy as np

__all__ = []


def _reader(mode):
    from ..vision.datasets import MNIST
    ds = MNIST(mode=mode)  # once per creator: reader() runs per epoch

    def reader():
        for img, label in ds:
            flat = np.asarray(img, "float32").reshape(-1)
            # contract: pixels in [-1, 1] (real data is [0,1]-normalized
            # so the clip is a no-op; the synthetic offline fallback is
            # unbounded gaussian and gets clamped into contract)
            flat = np.clip(flat * 2.0 - 1.0, -1.0, 1.0)
            yield flat, int(np.asarray(label).reshape(-1)[0])

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def fetch():
    pass
