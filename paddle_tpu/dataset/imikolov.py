"""Reference: dataset/imikolov.py — build_dict() + train/test(word_idx,
n) reader creators yielding n-gram tuples (or (src, trg) in SEQ
mode)."""
import numpy as np

__all__ = []


class DataType:
    NGRAM = 1
    SEQ = 2


def build_dict(min_word_freq=50):
    from ..text.datasets import Imikolov
    return dict(Imikolov(mode="train",
                         min_word_freq=min_word_freq).word_idx)


def _reader(mode, word_idx, n, data_type):
    from ..text.datasets import Imikolov
    dtype = "NGRAM" if data_type == DataType.NGRAM else "SEQ"
    ds = Imikolov(data_type=dtype, window_size=n, mode=mode)
    # keep ids valid indices into the caller's dict (they size their
    # embedding table by it)
    n_vocab = max(1, len(word_idx)) if word_idx else None

    def clamp(i):
        i = int(i)
        return i % n_vocab if n_vocab is not None else i

    def reader():
        for sample in ds:
            if dtype == "NGRAM":
                yield tuple(clamp(np.asarray(s).reshape(-1)[0])
                            for s in sample)
            else:
                src, trg = sample
                yield ([clamp(i) for i in np.asarray(src).reshape(-1)],
                       [clamp(i) for i in np.asarray(trg).reshape(-1)])

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader("train", word_idx, n, data_type)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader("test", word_idx, n, data_type)


def fetch():
    pass
