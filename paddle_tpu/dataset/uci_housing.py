"""Reference: dataset/uci_housing.py — train/test readers yielding
(13-dim float32 features, 1-dim target)."""
import numpy as np

__all__ = []


def _reader(mode):
    from ..text.datasets import UCIHousing
    ds = UCIHousing(mode=mode)  # once per creator

    def reader():
        for feat, price in ds:
            yield (np.asarray(feat, "float32"),
                   np.asarray(price, "float32").reshape(-1))

    return reader


def train():
    return _reader("train")


def test():
    return _reader("test")


def fetch():
    pass
