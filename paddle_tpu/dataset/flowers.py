"""Reference: dataset/flowers.py — train/test/valid reader creators
yielding (CHW float32 image, int label)."""
import numpy as np

__all__ = []


def _reader(mode, cycle=False, mapper=None):
    from ..vision.datasets import Flowers
    ds = Flowers(mode=mode)  # once per creator

    def reader():
        while True:
            for img, label in ds:
                sample = (np.asarray(img, "float32"),
                          int(np.asarray(label).reshape(-1)[0]))
                if mapper is not None:
                    sample = mapper(sample)
                yield sample
            if not cycle:
                break

    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader("train", cycle, mapper)


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _reader("test", cycle, mapper)


def valid(mapper=None, buffered_size=1024, use_xmap=True):
    return _reader("valid", mapper=mapper)


def fetch():
    pass
