"""Reference: dataset/wmt14.py — train/test(dict_size) reader creators
yielding (src_ids, trg_ids, trg_next_ids)."""
import numpy as np

__all__ = []


def _reader(mode, dict_size):
    from ..text.datasets import WMT14
    ds = WMT14(mode=mode, dict_size=dict_size)  # once per creator

    def reader():
        for sample in ds:
            yield tuple(list(np.asarray(f).reshape(-1)) for f in sample)

    return reader


def train(dict_size):
    return _reader("train", dict_size)


def test(dict_size):
    return _reader("test", dict_size)


def get_dict(dict_size, reverse=True):
    from ..text.datasets import WMT14
    ds = WMT14(mode="train", dict_size=dict_size)
    return (ds.get_dict("en", reverse=reverse),
            ds.get_dict("fr", reverse=reverse))


def fetch():
    pass
