"""Reference: dataset/conll05.py — SRL test reader + dict/embedding
queries."""
import numpy as np

__all__ = []


_ds_cache = []


def _ds():
    if not _ds_cache:
        from ..text.datasets import Conll05st
        _ds_cache.append(Conll05st())
    return _ds_cache[0]


def get_dict():
    ds = _ds()
    return ds.word_dict, ds.predicate_dict, ds.label_dict


def get_embedding():
    """Reference returns the downloaded emb file's contents; offline we
    derive a deterministic embedding table sized to the word dict."""
    word_dict = _ds().word_dict
    rng = np.random.RandomState(0)
    return rng.randn(len(word_dict), 32).astype("float32")


def test():
    def reader():
        for sample in _ds():
            yield tuple(np.asarray(f).reshape(-1) for f in sample)

    return reader


def fetch():
    pass
