"""Reference: dataset/common.py — DATA_HOME + download/md5 helpers.
Zero-egress: download() only resolves already-present local files."""
import hashlib
import os

from ..utils.download import DATA_HOME  # noqa: F401

__all__ = []


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Resolve the local cached path for a dataset file. This
    environment has no egress: if the file is not already under
    DATA_HOME/<module_name>, raise with the expected location (the
    class-based datasets used by the delegating readers fall back to
    synthetic data instead of calling this)."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(dirname,
                            save_name or url.split("/")[-1])
    if os.path.exists(filename):
        if md5sum and md5file(filename) != md5sum:
            raise RuntimeError(
                f"{filename} exists but its md5 does not match {md5sum} "
                f"(corrupt or truncated copy — replace the file)")
        return filename
    raise RuntimeError(
        f"dataset file not present at {filename} and this host has no "
        f"network egress; place the file there manually or use the "
        f"class-based paddle.vision/text datasets (synthetic fallback)")
