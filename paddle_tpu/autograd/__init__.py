"""paddle.autograd equivalent: PyLayer custom autograd + paddle.grad.

Reference parity: python/paddle/autograd/py_layer.py:192 (PyLayer) and
paddle/fluid/imperative/partial_grad_engine.cc (paddle.grad).
"""
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.engine import GradNode, run_backward
from ..core.dispatch import is_grad_enabled, no_grad, enable_grad  # noqa: F401


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved


class _PyLayerNode(GradNode):
    """GradNode whose backward calls the user's static backward()."""

    def __init__(self, layer_cls, ctx, input_tensors, out_avals):
        # op/key/closure unused; we override backward dispatch
        super().__init__(None, None, None, None, input_tensors, out_avals)
        self.layer_cls = layer_cls
        self.ctx = ctx


class PyLayerMeta(type):
    def __call__(cls, *args, **kwargs):
        raise RuntimeError("PyLayer is not instantiable; use .apply()")


class PyLayer:
    """User subclasses define @staticmethod forward(ctx, ...) and
    backward(ctx, *grads)."""

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        with no_grad():
            out = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(out, (list, tuple))
        outs = list(out) if multi else [out]
        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        record = is_grad_enabled() and any(not t.stop_gradient
                                           for t in tensor_inputs)
        if not record:
            return out
        out_avals = [(tuple(o.aval_shape()), o.value.dtype) for o in outs]
        node = _PyLayerNode(cls, ctx, tensor_inputs, out_avals)
        node.multi_out = multi

        layer_cls = cls

        class _Op:
            name = f"py_layer_{cls.__name__}"

            @staticmethod
            def vjp_fn(key, closure):
                def bwd(arrays, cts):
                    ct_tensors = [Tensor(c) for c in
                                  (cts if isinstance(cts, tuple) else (cts,))]
                    with no_grad():
                        gin = layer_cls.backward(ctx, *ct_tensors) \
                            if len(ct_tensors) > 1 else \
                            layer_cls.backward(ctx, ct_tensors[0])
                    gins = gin if isinstance(gin, (list, tuple)) else (gin,)
                    return tuple(g.value if isinstance(g, Tensor) else g
                                 for g in gins)
                return bwd

        node.op = _Op
        results = []
        for i, o in enumerate(outs):
            t = Tensor(o.value, stop_gradient=False)
            t._grad_node = (node, i)
            results.append(t)
        node.out_refs = results
        return tuple(results) if multi else results[0]

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *grads):
        raise NotImplementedError


def backward(tensors, grad_tensors=None, retain_graph=False):
    ts = tensors if isinstance(tensors, (list, tuple)) else [tensors]
    gs = grad_tensors if isinstance(grad_tensors, (list, tuple)) else \
        [grad_tensors] * len(ts)
    for t, g in zip(ts, gs):
        run_backward(t, g, retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad — compute grads of outputs wrt inputs without touching
    .grad of other leaves (reference: partial_grad_engine.cc)."""
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    # Snapshot .grad of every reachable leaf plus the requested inputs, zero
    # them, run backward, extract input grads, then restore the snapshots so
    # paddle.grad has no visible side effects on .grad.
    leaves = _reachable_leaves(outs)
    snapshot = {id(t): (t, t._grad) for t in leaves}
    for t in ins:
        snapshot.setdefault(id(t), (t, t._grad))
    for t, _ in snapshot.values():
        t._grad = None
    gouts = grad_outputs if isinstance(grad_outputs, (list, tuple)) else \
        [grad_outputs] * len(outs)
    retain = retain_graph if retain_graph is not None else create_graph
    try:
        for o, g in zip(outs, gouts):
            run_backward(o, g, retain_graph=bool(retain),
                         create_graph=bool(create_graph))
        results = []
        for t in ins:
            if t._grad is None and not allow_unused:
                raise RuntimeError(f"input {t.name} unused in graph "
                                   "(pass allow_unused=True)")
            results.append(t._grad)
    finally:
        # restore user-visible .grad even when backward raises — paddle.grad
        # must never wipe accumulated gradients
        for t, g in snapshot.values():
            t._grad = g
    return results


def _reachable_leaves(outs):
    leaves = []
    seen = set()
    stack = [o._grad_node[0] for o in outs if o._grad_node is not None]
    visited = set()
    while stack:
        node = stack.pop()
        if id(node) in visited:
            continue
        visited.add(id(node))
        for t in node.input_tensors:
            if t is None:
                continue
            if t._grad_node is not None:
                stack.append(t._grad_node[0])
            elif not t.stop_gradient and id(t) not in seen:
                seen.add(id(t))
                leaves.append(t)
    return leaves
