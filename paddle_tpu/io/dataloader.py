"""DataLoader.

Reference parity: python/paddle/fluid/reader.py:146 DataLoader +
dataloader_iter.py (single/multiprocess iters) + operators/reader/
buffered_reader.cc (async H2D double buffering). TPU-native:
num_workers>0 spawns worker PROCESSES (io/worker.py) that decode and
collate to numpy; large arrays travel through POSIX shared memory, and a
background thread double-buffers jax.device_put so the next batch's H2D
transfer overlaps the current step — the same overlap the reference gets
from its side-stream buffered reader.
"""
import queue
import threading

import numpy as np

from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler  # noqa: F401


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch, axis=0)
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    from ..core.tensor import Tensor
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch], axis=0)
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([s[i] for s in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.return_list = return_list
        self.use_buffer_reader = use_buffer_reader
        self.use_shared_memory = use_shared_memory
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self.persistent_workers = persistent_workers
        self.batch_size = batch_size
        self.drop_last = drop_last
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    @staticmethod
    def _to_tensors(collated):
        from ..core.tensor import Tensor
        if isinstance(collated, (list, tuple)):
            return [Tensor(c) if isinstance(c, np.ndarray) else c
                    for c in collated]
        if isinstance(collated, np.ndarray):
            return [Tensor(collated)]
        return collated

    def _make_batches(self):
        to_tensors = self._to_tensors

        if self._iterable_mode:
            bs = self.batch_size or 1  # None = per-sample (no batching)
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == bs:
                    yield to_tensors(self.collate_fn(buf))
                    buf = []
            if buf and not self.drop_last:
                yield to_tensors(self.collate_fn(buf))
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield to_tensors(self.collate_fn([self.dataset[i]]))
            return
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield to_tensors(self.collate_fn(batch))

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._make_batches()
            return
        yield from self._iter_multiprocess()

    def _convert_batch(self, batch, shm_holds):
        """Turn a decoded worker batch into consumer tensors and release
        its shm segments safely (exception-safe: segments are always
        freed)."""
        from .worker import _release
        import jax
        try:
            cpu_backend = jax.default_backend() == "cpu"
            if shm_holds and (cpu_backend
                              or not self._fast_convertible(batch)):
                # Materialize private copies of arrays that would
                # otherwise alias the shm buffer after release:
                # CPU-backend jax arrays can wrap host numpy zero-copy,
                # and structures _to_tensors leaves as raw numpy (dicts,
                # nested lists) alias it unconditionally.
                batch = self._copy_out(batch)
                _release(shm_holds)
                shm_holds = []
            tensors = self._to_tensors(batch)
            if shm_holds:
                # accelerator path: the H2D copy must land before the
                # shm segment goes away
                for t in tensors:
                    v = getattr(t, "value", None)
                    if hasattr(v, "block_until_ready"):
                        v.block_until_ready()
                _release(shm_holds)
                shm_holds = []
            return tensors
        finally:
            if shm_holds:
                _release(shm_holds)

    @classmethod
    def _copy_out(cls, obj):
        if isinstance(obj, np.ndarray):
            return np.array(obj, copy=True)
        if isinstance(obj, (list, tuple)):
            return type(obj)(cls._copy_out(o) for o in obj)
        if isinstance(obj, dict):
            return {k: cls._copy_out(v) for k, v in obj.items()}
        return obj

    @staticmethod
    def _fast_convertible(b):
        # shapes _to_tensors fully converts to device arrays: a bare
        # ndarray, or a flat list/tuple whose array entries are all
        # top-level (nested containers stay raw numpy inside)
        if isinstance(b, np.ndarray):
            return True
        if isinstance(b, (list, tuple)):
            return not any(isinstance(o, (list, tuple, dict)) for o in b)
        return False

    def _get_mp_iter(self):
        from .worker import _MultiprocessIter
        it = getattr(self, "_mp_iter", None)
        if it is not None and not it._shut \
                and all(w.is_alive() for w in it.workers):
            it.reset()
            return it
        self._mp_iter = None
        it = _MultiprocessIter(self)
        if it.persistent:
            self._mp_iter = it
        return it

    def _finish_epoch(self, mp_iter, completed):
        if completed and mp_iter.persistent and not mp_iter._shut:
            return  # keep the pool for the next epoch
        mp_iter._shutdown()
        if getattr(self, "_mp_iter", None) is mp_iter:
            self._mp_iter = None

    def _iter_multiprocess(self):
        """Worker processes collate; large arrays arrive via shared
        memory; with use_buffer_reader a background thread stages the
        next batches onto the device (double-buffered device_put — the
        analogue of the reference's buffered_reader side-stream H2D
        prefetch, operators/reader/buffered_reader.cc) and releases each
        shm segment once its transfer has landed."""
        mp_iter = self._get_mp_iter()

        if not self.use_buffer_reader:
            completed = False
            try:
                for batch, shm_holds in mp_iter:
                    yield self._convert_batch(batch, shm_holds)
                completed = True
            finally:
                self._finish_epoch(mp_iter, completed)
            return

        q = queue.Queue(maxsize=2)
        sentinel = object()
        err = []
        stop = threading.Event()

        def put(item):
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            completed = False
            try:
                for batch, shm_holds in mp_iter:
                    if not put(self._convert_batch(batch, shm_holds)):
                        return  # consumer abandoned the iterator
                completed = True
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                try:
                    self._finish_epoch(mp_iter, completed)
                except BaseException as e:
                    err.append(e)
                put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is sentinel:
                    break
                yield item
            if err:
                raise err[0]
        finally:
            stop.set()
            t.join(timeout=10.0)
