"""DataLoader.

Reference parity: python/paddle/fluid/reader.py:146 DataLoader +
dataloader_iter.py (single/multiprocess iters) + operators/reader/
buffered_reader.cc (async H2D double buffering). TPU-native: worker threads
(numpy collate releases the GIL for the heavy parts) feed a bounded queue;
device transfer happens via jax.device_put which is async, giving the same
overlap the reference gets from its side-stream buffered reader.
"""
import queue
import threading

import numpy as np

from .dataset import IterableDataset
from .sampler import BatchSampler, DistributedBatchSampler  # noqa: F401


def default_collate_fn(batch):
    """Stack samples into batched numpy arrays (reference:
    fluid/dataloader/collate.py default_collate_fn)."""
    sample = batch[0]
    if isinstance(sample, (np.ndarray, np.generic)):
        return np.stack(batch, axis=0)
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    from ..core.tensor import Tensor
    if isinstance(sample, Tensor):
        return np.stack([s.numpy() for s in batch], axis=0)
    if isinstance(sample, (list, tuple)):
        return type(sample)(default_collate_fn([s[i] for s in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([s[k] for s in batch]) for k in sample}
    return np.asarray(batch)


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(2, prefetch_factor)
        self.return_list = return_list
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            if batch_size is None:
                self.batch_sampler = None
                self.batch_size = None
            else:
                self.batch_sampler = BatchSampler(
                    dataset, shuffle=shuffle, batch_size=batch_size,
                    drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _make_batches(self):
        from ..core.tensor import Tensor

        def to_tensors(collated):
            if isinstance(collated, (list, tuple)):
                return [Tensor(c) if isinstance(c, np.ndarray) else c
                        for c in collated]
            if isinstance(collated, np.ndarray):
                return [Tensor(collated)]
            return collated

        if self._iterable_mode:
            buf = []
            for sample in self.dataset:
                buf.append(sample)
                if len(buf) == self.batch_size:
                    yield to_tensors(self.collate_fn(buf))
                    buf = []
            if buf and not self.drop_last:
                yield to_tensors(self.collate_fn(buf))
            return
        if self.batch_sampler is None:
            for i in range(len(self.dataset)):
                yield to_tensors(self.collate_fn([self.dataset[i]]))
            return
        for indices in self.batch_sampler:
            batch = [self.dataset[i] for i in indices]
            yield to_tensors(self.collate_fn(batch))

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._make_batches()
            return
        from ..core import native
        if self.use_buffer_reader and native.available():
            yield from self._iter_native()
            return
        # threaded prefetch pipeline: workers collate, main thread yields
        q = queue.Queue(maxsize=self.prefetch_factor * self.num_workers)
        sentinel = object()
        err = []

        def producer():
            try:
                for b in self._make_batches():
                    q.put(b)
            except BaseException as e:  # propagate to consumer
                err.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                break
            yield item
        if err:
            raise err[0]

    def _iter_native(self):
        """Batches flow through the C++ blocking queue (runtime_cpp) — the
        analogue of the reference's LoDTensorBlockingQueue between workers
        and the buffered reader."""
        import pickle
        from ..core import native
        from ..core.tensor import Tensor
        q = native.NativeBlockingQueue(
            capacity=self.prefetch_factor * self.num_workers)
        err = []

        def producer():
            try:
                for b in self._make_batches():
                    payload = [t.numpy() if isinstance(t, Tensor) else t
                               for t in b] if isinstance(b, list) else b
                    q.put_bytes(pickle.dumps(payload, protocol=4))
            except BaseException as e:
                err.append(e)
            finally:
                q.close()

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            raw = q.get_bytes()
            if raw is None:
                break
            batch = pickle.loads(raw)
            if isinstance(batch, list):
                batch = [Tensor(a) if isinstance(a, np.ndarray) else a
                         for a in batch]
            yield batch
        if err:
            raise err[0]
