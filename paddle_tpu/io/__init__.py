"""paddle.io equivalent: Dataset / DataLoader / samplers.

Reference parity: python/paddle/fluid/dataloader/ (Dataset, IterableDataset,
BatchSampler, DistributedBatchSampler) and python/paddle/fluid/reader.py:146
DataLoader. TPU-native design: instead of the reference's multiprocess
workers + shared-memory + C++ blocking queue + buffered_reader H2D prefetch
chain, we use a thread-pool fetcher feeding a bounded queue with
double-buffered jax.device_put — on TPU the expensive hop is host->HBM, and
async dispatch overlaps it with compute. (A C++ native queue backend lives
in runtime_cpp/ for the high-throughput path.)
"""
from .dataset import Dataset, IterableDataset, TensorDataset, Subset, \
    ChainDataset, ComposeDataset, random_split  # noqa: F401
from .sampler import Sampler, SequenceSampler, RandomSampler, BatchSampler, \
    DistributedBatchSampler, WeightedRandomSampler  # noqa: F401
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .worker import get_worker_info, WorkerInfo  # noqa: F401,E402
