"""Datasets (reference: python/paddle/fluid/dataloader/dataset.py)."""
import bisect

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset does not support indexing")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        from ..core.tensor import Tensor
        self.tensors = tensors
        lens = {t.shape[0] if isinstance(t, Tensor) else len(t)
                for t in tensors}
        assert len(lens) == 1, "tensors must have equal first dim"

    def __getitem__(self, idx):
        from ..core.tensor import Tensor
        return tuple(np.asarray(t.numpy()[idx]) if isinstance(t, Tensor)
                     else np.asarray(t[idx]) for t in self.tensors)

    def __len__(self):
        from ..core.tensor import Tensor
        t = self.tensors[0]
        return t.shape[0] if isinstance(t, Tensor) else len(t)


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __len__(self):
        return self.cumulative[-1]

    def __getitem__(self, idx):
        ds_idx = bisect.bisect_right(self.cumulative, idx)
        prev = self.cumulative[ds_idx - 1] if ds_idx > 0 else 0
        return self.datasets[ds_idx][idx - prev]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = datasets

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, tuple):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out = []
    start = 0
    for ln in lengths:
        out.append(Subset(dataset, perm[start:start + ln].tolist()))
        start += ln
    return out
