"""Multiprocess DataLoader workers with shared-memory tensor transport.

Reference parity: python/paddle/fluid/dataloader/worker.py:251
(_worker_loop), dataloader_iter.py:241 (_DataLoaderIterMultiProcess) and
paddle/fluid/memory/allocation/mmap_allocator.h (shared-memory transport
between workers and the main process). TPU-native shape: worker processes
decode/augment/collate to numpy; large arrays travel through POSIX shared
memory (multiprocessing.shared_memory) so the pipe carries only
descriptors; the main process wraps the shm buffer zero-copy and hands it
straight to jax.device_put, then unlinks.

Fork start method (Linux): the dataset is inherited, not pickled, and
workers never touch jax — only numpy + shm.
"""
import multiprocessing as mp
import os
import queue
import sys
import traceback

import numpy as np

# arrays at or above this many bytes ride shared memory; smaller ones are
# cheaper to pickle straight through the result queue
_SHM_MIN_BYTES = 1 << 14


class WorkerInfo:
    """Visible to dataset code inside a worker (reference:
    fluid/dataloader/worker.py WorkerInfo / paddle.io.get_worker_info)."""

    def __init__(self, id, num_workers, dataset, seed):  # noqa: A002
        self.id = id
        self.num_workers = num_workers
        self.dataset = dataset
        self.seed = seed

    def __repr__(self):
        return (f"WorkerInfo(id={self.id}, num_workers={self.num_workers}, "
                f"seed={self.seed})")


_worker_info = None


def get_worker_info():
    """Returns the WorkerInfo inside a DataLoader worker process, else
    None (reference: paddle.io.get_worker_info)."""
    return _worker_info


class _ExceptionWrapper:
    def __init__(self, exc):
        self.exc_type_name = type(exc).__name__
        self.msg = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.exc_type_name}:\n{self.msg}")


def _unregister_shm(shm):
    """The worker creates the segment but the main process unlinks it;
    detach the worker-side resource_tracker registration so worker exit
    doesn't unlink (or warn about) segments still in flight."""
    try:
        from multiprocessing import resource_tracker
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _encode(obj, use_shared_memory, shm_refs):
    """Recursively replace large numpy arrays with shm descriptors.
    Appends created SharedMemory objects to shm_refs (worker closes its
    mapping after the queue put)."""
    if isinstance(obj, np.ndarray):
        if (use_shared_memory and obj.nbytes >= _SHM_MIN_BYTES
                and obj.dtype != object):
            from multiprocessing import shared_memory
            # NOTE: no resource_tracker.unregister here. Workers are
            # forked AFTER the main process starts the tracker
            # (_MultiprocessIter calls ensure_running), so create
            # registers in the SHARED tracker; the main process's
            # attach re-register is a set no-op and its unlink
            # unregisters — balanced. A worker killed mid-encode leaves
            # the segment registered, so the tracker reclaims it at
            # exit instead of leaking it until reboot.
            shm = shared_memory.SharedMemory(create=True, size=obj.nbytes)
            dst = np.ndarray(obj.shape, dtype=obj.dtype, buffer=shm.buf)
            dst[...] = obj
            shm_refs.append(shm)
            return ("_shm", shm.name, obj.dtype.str, obj.shape)
        return obj
    if isinstance(obj, tuple):
        return ("_tuple", [_encode(o, use_shared_memory, shm_refs)
                           for o in obj])
    if isinstance(obj, list):
        return [_encode(o, use_shared_memory, shm_refs) for o in obj]
    if isinstance(obj, dict):
        return {k: _encode(v, use_shared_memory, shm_refs)
                for k, v in obj.items()}
    return obj


def _decode(obj, shm_holds):
    """Inverse of _encode in the main process. Attached SharedMemory
    objects are appended to shm_holds; the returned arrays alias their
    buffers, so the caller must keep shm_holds alive until the arrays are
    consumed (device_put), then close+unlink each."""
    if isinstance(obj, tuple) and obj and obj[0] == "_shm":
        from multiprocessing import shared_memory
        _, name, dtype_str, shape = obj
        # attach registers with the resource_tracker; the later unlink()
        # in _release/_unlink_encoded unregisters — balanced, so no
        # manual unregister here (that would double-unregister)
        shm = shared_memory.SharedMemory(name=name)
        shm_holds.append(shm)
        return np.ndarray(shape, dtype=np.dtype(dtype_str), buffer=shm.buf)
    if isinstance(obj, tuple) and obj and obj[0] == "_tuple":
        return tuple(_decode(o, shm_holds) for o in obj[1])
    if isinstance(obj, list):
        return [_decode(o, shm_holds) for o in obj]
    if isinstance(obj, dict):
        return {k: _decode(v, shm_holds) for k, v in obj.items()}
    return obj


def _unlink_encoded(obj):
    """Free shm segments referenced by a still-encoded batch without
    decoding it (shutdown path for never-consumed prefetched batches)."""
    if isinstance(obj, tuple) and obj and obj[0] == "_shm":
        from multiprocessing import shared_memory
        try:
            shm = shared_memory.SharedMemory(name=obj[1])
            shm.close()
            shm.unlink()
        except FileNotFoundError:
            pass
        except Exception:
            pass
        return
    if isinstance(obj, tuple) and obj and obj[0] == "_tuple":
        for o in obj[1]:
            _unlink_encoded(o)
    elif isinstance(obj, (list, tuple)):
        for o in obj:
            _unlink_encoded(o)
    elif isinstance(obj, dict):
        for v in obj.values():
            _unlink_encoded(v)


def _release(shm_holds):
    for shm in shm_holds:
        try:
            shm.close()
            shm.unlink()  # also unregisters from the resource_tracker
        except FileNotFoundError:
            # already unlinked elsewhere: balance the attach-register
            _unregister_shm(shm)


def _worker_loop(dataset, iterable_mode, collate_fn, index_queue,
                 result_queue, worker_id, num_workers, seed, init_fn,
                 use_shared_memory, batch_size, drop_last):
    """Runs in the child process. Pulls (idx, indices) tasks, collates,
    pushes (idx, encoded_batch). A None task means exit. For
    IterableDataset the task is (idx, count): the worker advances its own
    iterator (sharding via get_worker_info is the dataset's job,
    matching the reference's iterable semantics)."""
    global _worker_info
    _worker_info = WorkerInfo(worker_id, num_workers, dataset, seed)
    np.random.seed((seed + worker_id) % (2 ** 32))
    try:
        import random
        random.seed(seed + worker_id)
        if init_fn is not None:
            init_fn(worker_id)
        it = iter(dataset) if iterable_mode else None
        while True:
            task = index_queue.get()
            if task is None:
                break
            idx, indices = task
            try:
                if iterable_mode:
                    buf = []
                    for _ in range(indices):
                        try:
                            buf.append(next(it))
                        except StopIteration:
                            break
                    if not buf or (drop_last and len(buf) < indices):
                        result_queue.put((idx, ("_iter_end",)))
                        continue
                    batch = collate_fn(buf)
                else:
                    batch = collate_fn([dataset[i] for i in indices])
                shm_refs = []
                enc = _encode(batch, use_shared_memory, shm_refs)
                result_queue.put((idx, enc))
                for shm in shm_refs:
                    shm.close()  # main process owns the segment now
            except Exception as e:  # per-batch error -> main re-raises
                result_queue.put((idx, _ExceptionWrapper(e)))
    except KeyboardInterrupt:
        pass
    except Exception as e:
        try:
            result_queue.put((-1, _ExceptionWrapper(e)))
        except Exception:
            pass


class _MultiprocessIter:
    """Main-process side: task dispatch, order-restoring receive, worker
    liveness watch (reference: dataloader_iter.py:241 + the SIGCHLD
    watcher in imperative/data_loader.cc)."""

    def __init__(self, loader):
        self.loader = loader
        self._shut = False
        self.num_workers = loader.num_workers
        self.use_shared_memory = loader.use_shared_memory
        self.timeout = loader.timeout or 0
        # start the resource_tracker in THIS process before forking so
        # every worker inherits it: shm segments then live in one shared
        # registry (see the note in _encode)
        try:
            from multiprocessing import resource_tracker
            resource_tracker.ensure_running()
        except Exception:
            pass
        ctx = mp.get_context("fork" if hasattr(os, "fork") else "spawn")
        self.index_queue = ctx.Queue()
        self.result_queue = ctx.Queue()
        self.iterable_mode = loader._iterable_mode
        self.persistent = (loader.persistent_workers
                           and not self.iterable_mode)
        if self.iterable_mode:
            # Each worker iterates its own copy of the stream (reference
            # semantics: fluid/dataloader/worker.py — the dataset must
            # shard itself via get_worker_info() or every worker yields
            # the full stream).
            if self.num_workers > 1:
                import warnings
                warnings.warn(
                    "IterableDataset with num_workers>1: each worker "
                    "iterates the whole dataset; shard inside __iter__ "
                    "with paddle.io.get_worker_info() to avoid "
                    "duplicate samples")
        self.tasks = self._epoch_tasks()
        self.send_idx = 0
        self.rcvd_idx = 0
        self.reorder = {}
        self.iter_ended = False
        seed = int(np.random.randint(0, 2 ** 31 - 1))
        self.workers = []
        for wid in range(self.num_workers):
            w = ctx.Process(
                target=_worker_loop,
                args=(loader.dataset, self.iterable_mode, loader.collate_fn,
                      self.index_queue, self.result_queue, wid,
                      self.num_workers, seed, loader.worker_init_fn,
                      self.use_shared_memory, loader.batch_size,
                      loader.drop_last),
                daemon=True)
            w.start()
            self.workers.append(w)
        self.outstanding = 0
        self.max_outstanding = self.num_workers * loader.prefetch_factor
        self._prime()

    # -- dispatch ---------------------------------------------------------
    def _epoch_tasks(self):
        if self.iterable_mode:
            return None
        if self.loader.batch_sampler is None:
            # batch_size=None: per-sample mode (no batching), matching
            # the single-process _make_batches path
            return [[i] for i in range(len(self.loader.dataset))]
        return list(self.loader.batch_sampler)

    def reset(self):
        """Start a new epoch on the SAME worker pool
        (persistent_workers=True, map-style only). Re-lists the sampler
        so shuffling re-randomizes."""
        assert self.outstanding == 0 and not self.reorder
        self.tasks = self._epoch_tasks()
        self.send_idx = 0
        self.rcvd_idx = 0
        self._prime()

    def _have_more_tasks(self):
        if self.iterable_mode:
            return not self.iter_ended
        return self.send_idx < len(self.tasks)

    def _dispatch_one(self):
        if self.iterable_mode:
            self.index_queue.put(
                (self.send_idx, self.loader.batch_size or 1))
        else:
            self.index_queue.put((self.send_idx, self.tasks[self.send_idx]))
        self.send_idx += 1
        self.outstanding += 1

    def _prime(self):
        while self.outstanding < self.max_outstanding \
                and self._have_more_tasks():
            self._dispatch_one()

    # -- receive ----------------------------------------------------------
    def _check_workers(self):
        for w in self.workers:
            if not w.is_alive() and w.exitcode not in (0, None):
                self._shutdown()
                raise RuntimeError(
                    f"DataLoader worker pid={w.pid} exited unexpectedly "
                    f"with code {w.exitcode} (likely killed, e.g. OOM)")

    def _get(self):
        poll = self.timeout if self.timeout > 0 else 5.0
        while True:
            try:
                return self.result_queue.get(timeout=poll)
            except queue.Empty:
                self._check_workers()
                if self.timeout > 0:
                    self._shutdown()
                    raise RuntimeError(
                        f"DataLoader timed out after {self.timeout}s "
                        "waiting for a batch")

    def __iter__(self):
        return self

    def __next__(self):
        while True:
            if self.outstanding == 0 and not self._have_more_tasks():
                if not self.persistent:
                    self._shutdown()
                raise StopIteration
            if self.rcvd_idx in self.reorder:
                data = self.reorder.pop(self.rcvd_idx)
                self.rcvd_idx += 1
            else:
                idx, data = self._get()
                if idx == -1 or isinstance(data, _ExceptionWrapper):
                    self._shutdown()
                    data.reraise()
                if idx != self.rcvd_idx:
                    self.reorder[idx] = data
                    continue
                self.rcvd_idx += 1
            self.outstanding -= 1
            if isinstance(data, tuple) and data and data[0] == "_iter_end":
                self.iter_ended = True
                if self.outstanding == 0:
                    self._shutdown()
                    raise StopIteration
                continue
            self._prime()
            shm_holds = []
            batch = _decode(data, shm_holds)
            return batch, shm_holds

    def _shutdown(self):
        if self._shut:
            return
        self._shut = True
        try:
            for _ in self.workers:
                self.index_queue.put(None)
            for w in self.workers:
                w.join(timeout=2.0)
            for w in self.workers:
                if w.is_alive():
                    w.terminate()
        except Exception:
            pass
        try:
            self._drain_unlink()
        except Exception:
            pass

    def _drain_unlink(self):
        """Unlink shm segments referenced by batches that were produced
        but never consumed (in-flight prefetch when iteration stops early
        or errors). The workers unregistered these from their
        resource_tracker, so nobody else will free them."""
        for data in self.reorder.values():
            _unlink_encoded(data)
        self.reorder.clear()
        while True:
            try:
                _, data = self.result_queue.get(timeout=0.1)
            except queue.Empty:
                if not any(w.is_alive() for w in self.workers):
                    break
            except Exception:
                break
            else:
                _unlink_encoded(data)

    def __del__(self):
        self._shutdown()
