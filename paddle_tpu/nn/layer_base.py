"""Layer: the module base class.

Reference parity: python/paddle/fluid/dygraph/layers.py:81 (Layer) —
parameter/buffer/sublayer registries via __setattr__, state_dict /
set_state_dict, train/eval mode, forward pre/post hooks, apply, to().
"""
import collections

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core import dtype as dtype_mod
from . import initializer as init_mod

_layer_name_counters = collections.defaultdict(int)


class HookRemoveHelper:
    def __init__(self, hooks, hid):
        self._hooks = hooks
        self._hid = hid

    def remove(self):
        self._hooks.pop(self._hid, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        cls = type(self).__name__.lower()
        _layer_name_counters[cls] += 1
        self._full_name = f"{name_scope or cls}_{_layer_name_counters[cls] - 1}"
        self._dtype = dtype
        self.training = True
        self._parameters = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = 0

    # ---- attribute routing ----------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ first")
            params[name] = value
            self.__dict__.pop(name, None)
            return
        layers = self.__dict__.get("_sub_layers")
        if isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ first")
            layers[name] = value
            self.__dict__.pop(name, None)
            return
        if params is not None and name in params:
            if value is None:
                del params[name]
            else:
                params[name] = value
                return
        if layers is not None and name in layers:
            if value is None:
                del layers[name]
            else:
                layers[name] = value
                return
        buffers = self.__dict__.get("_buffers")
        if buffers is not None and name in buffers:
            if value is None or isinstance(value, Tensor):
                buffers[name] = value
                return
        object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        return list(super().__dir__()) + list(self._parameters) + \
            list(self._buffers) + list(self._sub_layers)

    # ---- construction helpers -------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Reference: layers.py create_parameter + ParamAttr resolution."""
        dtype = dtype or self._dtype or "float32"
        # the global initializer overrides any layer-passed default
        # (reference layer_helper_base.py:324: only attr.initializer
        # beats _global_weight_initializer)
        _g = init_mod.get_global_initializer(is_bias)
        if _g is not None:
            default_initializer = _g
        if default_initializer is None:
            if is_bias:
                default_initializer = init_mod.Constant(0.0)
            else:
                default_initializer = init_mod.XavierNormal()
        initializer = default_initializer
        learning_rate = 1.0
        trainable = True
        regularizer = None
        name = None
        if attr is not None and attr is not False:
            if isinstance(attr, init_mod.ParamAttr):
                if attr.initializer is not None:
                    initializer = attr.initializer
                learning_rate = attr.learning_rate
                trainable = attr.trainable
                regularizer = attr.regularizer
                name = attr.name
            elif isinstance(attr, init_mod.Initializer):
                initializer = attr
        if attr is False:
            return None
        value = initializer(tuple(int(s) for s in shape),
                            dtype_mod.to_jax_dtype(dtype))
        p = Parameter(value, name=name, trainable=trainable)
        p.optimize_attr["learning_rate"] = learning_rate
        p.regularizer = regularizer
        return p

    def add_parameter(self, name, parameter):
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        elif tensor is not None:
            tensor.persistable = True
        return tensor

    # ---- traversal -------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for pname, p in layer._parameters.items():
                if p is None or id(p) in seen:
                    continue
                seen.add(id(p))
                yield (f"{name}.{pname}" if name else pname), p
            if not include_sublayers:
                break

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix,
                                                include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                yield (f"{name}.{bname}" if name else bname), b
            if not include_sublayers:
                break

    def children(self):
        for _, l in self.named_children():
            yield l

    def named_children(self):
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            sub_prefix = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=sub_prefix, include_self=True)

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def full_name(self):
        return self._full_name

    # ---- modes -----------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # ---- state dict ------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix):
            dest[name] = p
        for name, layer in self.named_sublayers(
                prefix=structured_name_prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or bname in layer._non_persistable_buffer_names:
                    continue
                dest[f"{name}.{bname}" if name else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        own = self.state_dict()
        missing = []
        for name, tgt in own.items():
            if name not in state_dict:
                missing.append(name)
                continue
            src = state_dict[name]
            arr = src.value if isinstance(src, Tensor) else jnp.asarray(src)
            if tuple(arr.shape) != tuple(tgt.aval_shape()):
                raise ValueError(
                    f"shape mismatch for {name}: {arr.shape} vs {tgt.shape}")
            tgt.value = jnp.asarray(arr, tgt.value.dtype)
        # let layers re-derive transient python state from loaded buffers
        # (e.g. quant observers marking themselves calibrated)
        for _, layer in self.named_sublayers(include_self=True):
            hook = getattr(layer, "_after_load_state_dict", None)
            if hook is not None:
                hook()
        return missing

    set_dict = set_state_dict
    load_dict = set_state_dict

    # ---- dtype / device --------------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            jdt = dtype_mod.to_jax_dtype(dtype)
            for p in self.parameters():
                p.value = p.value.astype(jdt)
            for b in self.buffers():
                if jnp.issubdtype(b.value.dtype, jnp.floating):
                    b.value = b.value.astype(jdt)
            self._dtype = dtype_mod.to_paddle_dtype(dtype).name
        return self

    def float(self):
        return self.to(dtype="float32")

    def half(self):
        return self.to(dtype="float16")

    def bfloat16(self):
        return self.to(dtype="bfloat16")

    astype = to

    # ---- hooks -----------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook):
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ---- call ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            out = hook(self, inputs, outputs)
            if out is not None:
                outputs = out
        return outputs

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, child in self._sub_layers.items():
            child_repr = repr(child).split("\n")
            child_repr = "\n  ".join(child_repr)
            lines.append(f"({name}): {child_repr}")
        main = type(self).__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()
