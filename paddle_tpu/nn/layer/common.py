"""Common layers: Linear, Dropout, Embedding, Flatten, etc.

Reference parity: python/paddle/nn/layer/common.py.
"""
from ..layer_base import Layer
from .. import initializer as init_mod
from ...ops import nn_ops, manipulation


class Linear(Layer):
    """Reference: nn.Linear — weight shape [in_features, out_features]."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=init_mod.ParamAttr._to_attr(weight_attr))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_features,), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def forward(self, x):
        return nn_ops.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.mode = mode

    def forward(self, x):
        return nn_ops.dropout(x, p=self.p, training=self.training,
                              mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return nn_ops.dropout2d(x, p=self.p, training=self.training)


class Embedding(Layer):
    """Reference: nn.Embedding over lookup_table_v2."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self._sparse = bool(sparse)
        attr = init_mod.ParamAttr._to_attr(weight_attr)
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=attr,
            default_initializer=init_mod.Normal(0.0, 1.0) if (
                attr is None or attr.initializer is None) else None)
        if padding_idx is not None:
            import jax.numpy as jnp
            w = self.weight.value
            pi = padding_idx if padding_idx >= 0 else num_embeddings + padding_idx
            self.weight.value = w.at[pi].set(jnp.zeros_like(w[pi]))

    def forward(self, x):
        return nn_ops.embedding(x, self.weight,
                                padding_idx=self._padding_idx,
                                sparse=self._sparse)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return manipulation.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Pad2D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value
        self.data_format = data_format

    def forward(self, x):
        return manipulation.pad(x, self.padding, self.mode, self.value,
                                self.data_format)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.size = size
        self.scale_factor = scale_factor
        self.mode = mode
        self.align_corners = align_corners

    def forward(self, x):
        return nn_ops.interpolate(x, self.size, self.scale_factor, self.mode,
                                  self.align_corners)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "bilinear", True)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW",
                 name=None):
        super().__init__(size, scale_factor, "nearest", False)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.upscale_factor = upscale_factor

    def forward(self, x):
        return nn_ops.pixel_shuffle(x, self.upscale_factor)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis = axis
        self.eps = eps

    def forward(self, x1, x2):
        return nn_ops.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    """Reference: nn.Bilinear — out[b,o] = x1[b,:] W[o] x2[b,:]^T + b."""

    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter(
            (out_features, in1_features, in2_features),
            attr=init_mod.ParamAttr._to_attr(weight_attr))
        self.bias = None if bias_attr is False else self.create_parameter(
            (1, out_features), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def forward(self, x1, x2):
        from ...ops import math as math_ops
        out = math_ops.einsum("bi,oij,bj->bo", x1, self.weight, x2)
        if self.bias is not None:
            out = math_ops.add(out, self.bias)
        return out


class Pad1D(Layer):
    """Reference: nn/layer/common.py Pad1D over NCL input."""

    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCL", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value

    def forward(self, x):
        return manipulation.pad(x, self.padding, self.mode, self.value,
                                "NCL")


class Pad3D(Layer):
    def __init__(self, padding, mode="constant", value=0.0,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.padding = padding
        self.mode = mode
        self.value = value

    def forward(self, x):
        return manipulation.pad(x, self.padding, self.mode, self.value,
                                "NCDHW")


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return nn_ops.dropout3d(x, self.p, training=self.training)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return nn_ops.alpha_dropout(x, self.p, training=self.training)


class PairwiseDistance(Layer):
    """Reference: nn/layer/distance.py PairwiseDistance."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.p = p
        self.epsilon = epsilon
        self.keepdim = keepdim

    def forward(self, x, y):
        from ...ops import math as m, reduction as r
        diff = m.subtract(x, y)
        return r.norm(diff, p=self.p, axis=-1, keepdim=self.keepdim)


class Unfold(Layer):
    """Reference: nn/layer/common.py Unfold (im2col)."""

    def __init__(self, kernel_sizes, dilations=1, paddings=0, strides=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        k, s, p, d = self.args
        return manipulation.unfold(x, k, strides=s, paddings=p,
                                   dilations=d)
