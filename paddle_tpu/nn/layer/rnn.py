"""Recurrent layers: LSTM / GRU / SimpleRNN.

Reference parity: python/paddle/nn/layer/rnn.py (RNNBase over C++ cudnn
rnn op / rnn_op.cc). TPU-native design: the time loop is a lax.scan inside
one registered op, so XLA compiles the whole sequence into a single fused
loop; gate matmuls batch onto the MXU. Weight layout matches paddle:
weight_ih [gates*hidden, input], weight_hh [gates*hidden, hidden].
"""
import jax
import jax.numpy as jnp

from ..layer_base import Layer
from .. import initializer as init_mod
from ...core.dispatch import register_op
from ...core.tensor import Tensor
from ...ops import manipulation


@register_op("lstm_layer")
def _lstm_layer(x, h0, c0, w_ih, w_hh, b_ih, b_hh, *, reverse):
    """x: [seq, batch, input] (time-major internally). Returns (y, h, c)."""
    def step(carry, xt):
        h, c = carry
        gates = xt @ w_ih.T + h @ w_hh.T
        if b_ih is not None:
            gates = gates + b_ih + b_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new

    (h, c), ys = jax.lax.scan(step, (h0, c0), x, reverse=reverse)
    if reverse:
        pass  # scan(reverse=True) already emits outputs aligned to input order
    return ys, h, c


@register_op("gru_layer")
def _gru_layer(x, h0, w_ih, w_hh, b_ih, b_hh, *, reverse):
    def step(h, xt):
        gi = xt @ w_ih.T
        gh = h @ w_hh.T
        if b_ih is not None:
            gi = gi + b_ih
            gh = gh + b_hh
        ir, iz, ic = jnp.split(gi, 3, axis=-1)
        hr, hz, hc = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(ic + r * hc)
        h_new = (1.0 - z) * n + z * h
        return h_new, h_new

    h, ys = jax.lax.scan(step, h0, x, reverse=reverse)
    return ys, h


@register_op("simple_rnn_layer")
def _simple_rnn_layer(x, h0, w_ih, w_hh, b_ih, b_hh, *, reverse, activation):
    act = jnp.tanh if activation == "tanh" else jax.nn.relu

    def step(h, xt):
        z = xt @ w_ih.T + h @ w_hh.T
        if b_ih is not None:
            z = z + b_ih + b_hh
        h_new = act(z)
        return h_new, h_new

    h, ys = jax.lax.scan(step, h0, x, reverse=reverse)
    return ys, h


class RNNBase(Layer):
    GATES = {"LSTM": 4, "GRU": 3, "RNN_TANH": 1, "RNN_RELU": 1}

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = 2 if direction in ("bidirect", "bidirectional") else 1
        g = self.GATES[mode]
        std = 1.0 / (hidden_size ** 0.5)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.bidirect):
                in_sz = input_size if layer == 0 else hidden_size * self.bidirect
                suffix = "_reverse" if d else ""
                w_ih = self.create_parameter(
                    (g * hidden_size, in_sz), weight_ih_attr,
                    default_initializer=init_mod.Uniform(-std, std))
                w_hh = self.create_parameter(
                    (g * hidden_size, hidden_size), weight_hh_attr,
                    default_initializer=init_mod.Uniform(-std, std))
                b_ih = self.create_parameter(
                    (g * hidden_size,), bias_ih_attr, is_bias=True,
                    default_initializer=init_mod.Uniform(-std, std))
                b_hh = self.create_parameter(
                    (g * hidden_size,), bias_hh_attr, is_bias=True,
                    default_initializer=init_mod.Uniform(-std, std))
                names = [f"weight_ih_l{layer}{suffix}",
                         f"weight_hh_l{layer}{suffix}",
                         f"bias_ih_l{layer}{suffix}",
                         f"bias_hh_l{layer}{suffix}"]
                for n, p in zip(names, [w_ih, w_hh, b_ih, b_hh]):
                    self.add_parameter(n, p)
                self._all_weights.append(names)

    def _run_layer(self, x, h0, c0, names, reverse):
        w_ih = getattr(self, names[0])
        w_hh = getattr(self, names[1])
        b_ih = getattr(self, names[2])
        b_hh = getattr(self, names[3])
        if self.mode == "LSTM":
            return _lstm_layer(x, h0, c0, w_ih, w_hh, b_ih, b_hh,
                               reverse=reverse)
        if self.mode == "GRU":
            y, h = _gru_layer(x, h0, w_ih, w_hh, b_ih, b_hh, reverse=reverse)
            return y, h, None
        act = "tanh" if self.mode == "RNN_TANH" else "relu"
        y, h = _simple_rnn_layer(x, h0, w_ih, w_hh, b_ih, b_hh,
                                 reverse=reverse, activation=act)
        return y, h, None

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        x = inputs
        if not self.time_major:
            x = manipulation.transpose(x, (1, 0, 2))
        seq, batch = x.shape[0], x.shape[1]
        nstates = self.num_layers * self.bidirect
        if initial_states is None:
            h0_all = ops.creation.zeros((nstates, batch, self.hidden_size),
                                        dtype=x.value.dtype)
            c0_all = ops.creation.zeros((nstates, batch, self.hidden_size),
                                        dtype=x.value.dtype) \
                if self.mode == "LSTM" else None
        else:
            if self.mode == "LSTM":
                h0_all, c0_all = initial_states
            else:
                h0_all, c0_all = initial_states, None
        h_outs, c_outs = [], []
        idx = 0
        for layer in range(self.num_layers):
            outs = []
            for d in range(self.bidirect):
                h0 = h0_all[idx]
                c0 = c0_all[idx] if c0_all is not None else None
                y, h, c = self._run_layer(x, h0, c0, self._all_weights[idx],
                                          reverse=bool(d))
                outs.append(y)
                h_outs.append(h)
                if c is not None:
                    c_outs.append(c)
                idx += 1
            x = outs[0] if len(outs) == 1 else manipulation.concat(outs, axis=-1)
            if self.dropout and layer < self.num_layers - 1:
                x = ops.nn_ops.dropout(x, p=self.dropout,
                                       training=self.training)
        y = x
        if not self.time_major:
            y = manipulation.transpose(y, (1, 0, 2))
        h_final = manipulation.stack(h_outs, axis=0)
        if self.mode == "LSTM":
            c_final = manipulation.stack(c_outs, axis=0)
            return y, (h_final, c_final)
        return y, h_final


class LSTM(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("LSTM", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class GRU(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__("GRU", input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class SimpleRNN(RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers,
                         direction, time_major, dropout, weight_ih_attr,
                         weight_hh_attr, bias_ih_attr, bias_hh_attr)


class LSTMCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / (hidden_size ** 0.5)
        self.weight_ih = self.create_parameter(
            (4 * hidden_size, input_size), weight_ih_attr,
            default_initializer=init_mod.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (4 * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=init_mod.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            (4 * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init_mod.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            (4 * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init_mod.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ... import ops
        if states is None:
            b = inputs.shape[0]
            h = ops.creation.zeros((b, self.hidden_size), inputs.value.dtype)
            c = ops.creation.zeros((b, self.hidden_size), inputs.value.dtype)
        else:
            h, c = states
        x1 = manipulation.unsqueeze(inputs, axis=0)
        y, h_new, c_new = _lstm_layer(x1, h, c, self.weight_ih,
                                      self.weight_hh, self.bias_ih,
                                      self.bias_hh, reverse=False)
        return h_new, (h_new, c_new)


class GRUCell(Layer):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.hidden_size = hidden_size
        std = 1.0 / (hidden_size ** 0.5)
        self.weight_ih = self.create_parameter(
            (3 * hidden_size, input_size), weight_ih_attr,
            default_initializer=init_mod.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (3 * hidden_size, hidden_size), weight_hh_attr,
            default_initializer=init_mod.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            (3 * hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init_mod.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            (3 * hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init_mod.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ... import ops
        if states is None:
            b = inputs.shape[0]
            states = ops.creation.zeros((b, self.hidden_size),
                                        inputs.value.dtype)
        x1 = manipulation.unsqueeze(inputs, axis=0)
        y, h_new = _gru_layer(x1, states, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh, reverse=False)
        return h_new, h_new


class RNNCellBase(Layer):
    """Reference: paddle.nn.RNNCellBase — base protocol for cells usable
    with paddle.nn.RNN / BiRNN / dynamic_decode: forward(inputs, states)
    -> (outputs, new_states), plus get_initial_states."""

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        from ... import ops
        b = batch_ref.shape[batch_dim_idx]
        hs = getattr(self, "hidden_size")
        dt = dtype or "float32"
        if isinstance(self, LSTMCell):
            return (ops.creation.full((b, hs), init_value, dt),
                    ops.creation.full((b, hs), init_value, dt))
        return ops.creation.full((b, hs), init_value, dt)

    @property
    def state_shape(self):
        hs = getattr(self, "hidden_size")
        if isinstance(self, LSTMCell):
            return ((hs,), (hs,))
        return (hs,)


class SimpleRNNCell(RNNCellBase):
    """Reference: paddle.nn.SimpleRNNCell (tanh/relu single-gate)."""

    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.activation = activation
        std = 1.0 / (hidden_size ** 0.5)
        self.weight_ih = self.create_parameter(
            (hidden_size, input_size), weight_ih_attr,
            default_initializer=init_mod.Uniform(-std, std))
        self.weight_hh = self.create_parameter(
            (hidden_size, hidden_size), weight_hh_attr,
            default_initializer=init_mod.Uniform(-std, std))
        self.bias_ih = self.create_parameter(
            (hidden_size,), bias_ih_attr, is_bias=True,
            default_initializer=init_mod.Uniform(-std, std))
        self.bias_hh = self.create_parameter(
            (hidden_size,), bias_hh_attr, is_bias=True,
            default_initializer=init_mod.Uniform(-std, std))

    def forward(self, inputs, states=None):
        from ...ops import math as m, nn_ops
        if states is None:
            states = self.get_initial_states(inputs)
        pre = m.add(
            m.add(m.matmul(inputs, manipulation.t(self.weight_ih)),
                  self.bias_ih),
            m.add(m.matmul(states, manipulation.t(self.weight_hh)),
                  self.bias_hh))
        out = nn_ops.relu(pre) if self.activation == "relu" \
            else m.tanh(pre)
        return out, out


class RNN(Layer):
    """Reference: paddle.nn.RNN — wraps ANY RNNCellBase cell, scanning it
    over the time axis (python loop: the cell is an arbitrary Layer; under
    to_static the unrolled steps compile into one XLA program)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ... import ops
        x = inputs if self.time_major else \
            manipulation.transpose(inputs, (1, 0, 2))
        T = x.shape[0]
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        states = initial_states
        outs = [None] * T
        for t in steps:
            y, states = self.cell(x[t], states)
            outs[t] = y
        out = manipulation.stack(outs, axis=0)
        if not self.time_major:
            out = manipulation.transpose(out, (1, 0, 2))
        return out, states


class BiRNN(Layer):
    """Reference: paddle.nn.BiRNN — forward + backward cells, outputs
    concatenated on the feature axis."""

    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        ifw = ibw = None
        if initial_states is not None:
            ifw, ibw = initial_states
        out_f, st_f = self.rnn_fw(inputs, ifw)
        out_b, st_b = self.rnn_bw(inputs, ibw)
        out = manipulation.concat([out_f, out_b], axis=-1)
        return out, (st_f, st_b)


class BeamSearchDecoder(Layer):
    """Reference: paddle.nn.BeamSearchDecoder — beam expansion over a
    cell + output layer; used through dynamic_decode."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        super().__init__()
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn


def dynamic_decode(decoder, inits=None, max_step_num=32, **kwargs):
    """Reference: paddle.nn.dynamic_decode (rnn.py dynamic_decode +
    gather_tree finalize). Greedy-within-beam decode driven on the host;
    returns (ids [B, T, beam], final_states)."""
    import numpy as np
    from ... import ops
    from ...ops import nn_ops, math as m
    cell = decoder.cell
    beam = decoder.beam_size
    # fake a batch from inits or default batch 1
    if inits is None:
        raise ValueError("dynamic_decode requires initial states (inits)")
    states = inits
    h0 = states[0] if isinstance(states, (tuple, list)) else states
    b = h0.shape[0]
    # tile beams into the batch: [B*beam, ...]
    def tile(t):
        return manipulation.reshape(
            manipulation.tile(manipulation.unsqueeze(t, 1),
                              (1, beam, 1)), (b * beam, -1))
    if isinstance(states, (tuple, list)):
        states = type(states)(tile(s) for s in states)
    else:
        states = tile(states)
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    tok = ops.creation.full((b * beam,), decoder.start_token, "int64")
    # beam 0 starts live, beams 1..k-1 at -inf: identical scores would
    # make every beam pick the same token forever (greedy x beam_size)
    init_lp = np.full((b, beam), -1e9, np.float32)
    init_lp[:, 0] = 0.0
    log_probs = Tensor(jnp.asarray(init_lp))
    ids_steps = []
    parents_steps = []
    finished = jnp.zeros((b, beam), bool)
    end = decoder.end_token
    for _ in range(max_step_num):
        emb = decoder.embedding_fn(tok) if decoder.embedding_fn \
            else manipulation.unsqueeze(m.cast(tok, "float32"), -1)
        out, states = cell(emb, states)
        logits = decoder.output_fn(out) if decoder.output_fn else out
        logp = nn_ops.log_softmax(logits, axis=-1)  # [B*beam, V]
        V = logp.shape[-1]
        logp_v = manipulation.reshape(logp, (b, beam, V)).value
        # freeze finished beams (reference dynamic_decode): they may
        # only re-emit end_token, at zero additional cost
        frozen = jnp.full((V,), -1e9, logp_v.dtype).at[end].set(0.0)
        logp_v = jnp.where(finished[..., None], frozen, logp_v)
        logp = Tensor(logp_v)
        total = m.add(manipulation.unsqueeze(log_probs, -1), logp)
        flat = manipulation.reshape(total, (b, beam * V))
        top_v, top_i = ops.search.topk(flat, beam, axis=-1)
        parent = m.cast(ops.math.floor_divide(
            top_i, ops.creation.full((1,), V, "int64")), "int64")
        word = ops.math.remainder(
            top_i, ops.creation.full((1,), V, "int64"))
        log_probs = top_v
        ids_steps.append(word)
        parents_steps.append(parent)
        # carry finished-ness through the beam regather, then mark new
        # end_token emissions
        finished = jnp.take_along_axis(finished, parent.value, axis=-1)
        finished = finished | (word.value == end)
        # regather states by parent beam
        flat_parent = (parent.value + (jnp.arange(b) * beam)[:, None]
                       ).reshape(-1)
        def regather(s):
            from ...core.tensor import Tensor
            return Tensor(jnp.take(s.value, flat_parent, axis=0))
        if isinstance(states, (tuple, list)):
            states = type(states)(regather(s) for s in states)
        else:
            states = regather(states)
        tok = manipulation.reshape(word, (b * beam,))
    ids = manipulation.stack(ids_steps, axis=0)        # [T, B, beam]
    parents = manipulation.stack(parents_steps, axis=0)
    seqs = nn_ops.gather_tree(ids, parents)
    return manipulation.transpose(seqs, (1, 0, 2)), states
