"""Normalization layers (reference: python/paddle/nn/layer/norm.py)."""
import jax.numpy as jnp

from ..layer_base import Layer
from .. import initializer as init_mod
from ...core.tensor import Tensor
from ...ops import nn_ops


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,),
                                                       jnp.float32),
                                             persistable=True))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,),
                                                          jnp.float32),
                                                 persistable=True))

    def forward(self, x):
        return nn_ops.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self._momentum,
            epsilon=self._epsilon, data_format=self._data_format,
            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}"


class BatchNorm(_BatchNormBase):
    """Legacy fluid.dygraph.BatchNorm-compatible entry."""


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCL",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, "NCL", use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCDHW",
                 use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr,
                         bias_attr, data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """On TPU/SPMD, batch stats are computed over the global (sharded) batch
    automatically when the step runs under pjit with a dp-sharded input —
    matching reference SyncBatchNorm semantics without a special kernel
    (reference: python/paddle/nn/layer/norm.py SyncBatchNorm over
    sync_batch_norm op)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        n = 1
        for s in self._normalized_shape:
            n *= s
        self.weight = None if weight_attr is False else self.create_parameter(
            (n,), attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (n,), attr=init_mod.ParamAttr._to_attr(bias_attr), is_bias=True)

    def forward(self, x):
        return nn_ops.layer_norm(x, self._normalized_shape, self.weight,
                                 self.bias, self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            (num_channels,), attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_channels,), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def forward(self, x):
        return nn_ops.group_norm(x, self._num_groups, self.weight, self.bias,
                                 self._epsilon)


class InstanceNorm2D(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        self.scale = None if weight_attr is False else self.create_parameter(
            (num_features,), attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            (num_features,), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def forward(self, x):
        return nn_ops.instance_norm(x, weight=self.scale, bias=self.bias,
                                    epsilon=self._epsilon)


InstanceNorm1D = InstanceNorm2D
InstanceNorm3D = InstanceNorm2D


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0,
                 data_format="NCHW", name=None):
        super().__init__()
        self.size, self.alpha, self.beta, self.k = size, alpha, beta, k

    def forward(self, x):
        return nn_ops.local_response_norm(x, self.size, self.alpha, self.beta,
                                          self.k)


class SpectralNorm(Layer):
    """Reference: paddle.nn.SpectralNorm (spectral_norm_op.cc; python
    surface fluid/layers/nn.py:3650): power-iteration estimate of the
    weight's largest singular value sigma; forward(weight) returns
    weight / sigma. weight_u/weight_v are persistent buffers refreshed
    each forward, as in the reference op (u/v treated as constants for
    the gradient)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 dtype="float32", name=None):
        super().__init__()
        self._dim = int(dim)
        self._power_iters = int(power_iters)
        self._eps = float(eps)
        self._shape = tuple(int(s) for s in weight_shape)
        h = self._shape[self._dim]
        w = 1
        for i, s in enumerate(self._shape):
            if i != self._dim:
                w *= s
        rs_u = init_mod.Normal(0.0, 1.0)
        self.register_buffer(
            "weight_u", Tensor(jnp.asarray(rs_u((h,), jnp.float32)),
                               persistable=True))
        self.register_buffer(
            "weight_v", Tensor(jnp.asarray(rs_u((w,), jnp.float32)),
                               persistable=True))

    def forward(self, weight):
        out, u_n, v_n = nn_ops.spectral_norm(
            weight, self.weight_u, self.weight_v, dim=self._dim,
            power_iters=self._power_iters, eps=self._eps)
        # refresh the power-iteration state (reference: the op writes U/V
        # back in place); buffers are stop_gradient so no graph grows
        self.weight_u.value = u_n.value
        self.weight_v.value = v_n.value
        return out
