"""Pooling layers (reference: python/paddle/nn/layer/pooling.py)."""
from ..layer_base import Layer
from ...ops import nn_ops


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.ceil_mode = ceil_mode

    def forward(self, x):
        return nn_ops.max_pool2d(x, self.k, self.s, self.p, self.ceil_mode)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCHW",
                 name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive = exclusive

    def forward(self, x):
        return nn_ops.avg_pool2d(x, self.k, self.s, self.p,
                                 exclusive=self.exclusive)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x):
        return nn_ops.max_pool1d(x, self.k, self.s, self.p)


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding
        self.exclusive = exclusive

    def forward(self, x):
        return nn_ops.avg_pool1d(x, self.k, self.s, self.p, self.exclusive)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format="NCHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_avg_pool2d(x, self.output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_max_pool2d(x, self.output_size)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        from ...ops import manipulation
        x4 = manipulation.unsqueeze(x, axis=2)
        out = nn_ops.adaptive_avg_pool2d(x4, (1, self.output_size))
        return manipulation.squeeze(out, axis=2)


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x):
        return nn_ops.max_pool3d(x, self.k, self.s, self.p)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None,
                 data_format="NCDHW", name=None):
        super().__init__()
        self.k, self.s, self.p = kernel_size, stride, padding

    def forward(self, x):
        return nn_ops.avg_pool3d(x, self.k, self.s, self.p)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_max_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return nn_ops.adaptive_max_pool1d(x, self.output_size)
