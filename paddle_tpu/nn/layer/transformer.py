"""Transformer layers.

Reference parity: python/paddle/nn/layer/transformer.py (MultiHeadAttention,
TransformerEncoder/DecoderLayer, Transformer). Attention lowers to batched
matmuls that XLA tiles onto the MXU; a fused Pallas flash-attention path is
available through nn.functional.scaled_dot_product_attention when shapes
are large (see ops/attention.py).
"""
from ..layer_base import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from ...ops import nn_ops, math as math_ops, manipulation


def _convert_attn_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    from ...core.tensor import Tensor
    import jax.numpy as jnp
    v = attn_mask.value
    if v.dtype == jnp.bool_:
        neg = jnp.asarray(-1e9, dtype)
        return Tensor(jnp.where(v, jnp.zeros((), dtype), neg))
    return attn_mask


class MultiHeadAttention(Layer):
    """Reference: nn.MultiHeadAttention — q/k/v/out projections + scaled
    dot-product attention; supports cache for decoding."""

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split_heads(self, x):
        b, s, _ = x.shape
        x = manipulation.reshape(x, (b, s, self.num_heads, self.head_dim))
        return manipulation.transpose(x, (0, 2, 1, 3))

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = query if value is None else value
        q = self._split_heads(self.q_proj(query))
        k = self._split_heads(self.k_proj(key))
        v = self._split_heads(self.v_proj(value))
        if cache is not None:
            k = manipulation.concat([cache[0], k], axis=2)
            v = manipulation.concat([cache[1], v], axis=2)
            new_cache = (k, v)
        scale = self.head_dim ** -0.5
        qk = math_ops.matmul(math_ops.scale(q, scale), k, transpose_y=True)
        attn_mask = _convert_attn_mask(attn_mask, qk.value.dtype)
        if attn_mask is not None:
            qk = math_ops.add(qk, attn_mask)
        weights = nn_ops.softmax(qk, axis=-1)
        if self.dropout:
            weights = nn_ops.dropout(weights, p=self.dropout,
                                     training=self.training)
        out = math_ops.matmul(weights, v)
        out = manipulation.transpose(out, (0, 2, 1, 3))
        b, s = out.shape[0], out.shape[1]
        out = manipulation.reshape(out, (b, s, self.embed_dim))
        out = self.out_proj(out)
        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None:
            outs.append(new_cache)
        return out if len(outs) == 1 else tuple(outs)

    def gen_cache(self, key, value=None, type=None):  # noqa: A002
        from ... import ops
        b = key.shape[0]
        k = ops.creation.zeros((b, self.num_heads, 0, self.head_dim))
        v = ops.creation.zeros((b, self.num_heads, 0, self.head_dim))
        return (k, v)


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.activation = getattr(nn_ops, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        src = math_ops.add(residual, self.dropout1(src))
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.dropout(self.activation(self.linear1(src))))
        src = math_ops.add(residual, self.dropout2(src))
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        from .container import LayerList
        self.layers = LayerList([encoder_layer] + [
            copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask)
            else:
                output, cache[i] = mod(output, src_mask, cache[i])
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, cache)


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.activation = getattr(nn_ops, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        tgt = math_ops.add(residual, self.dropout1(tgt))
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        tgt = math_ops.add(residual, self.dropout2(tgt))
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = math_ops.add(residual, self.dropout3(tgt))
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        from .container import LayerList
        self.layers = LayerList([decoder_layer] + [
            copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        for mod in self.layers:
            output = mod(output, memory, tgt_mask, memory_mask)
        if self.norm is not None:
            output = self.norm(output)
        return output


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            enc_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            self.encoder = TransformerEncoder(
                enc_layer, num_encoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dec_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            self.decoder = TransformerDecoder(
                dec_layer, num_decoder_layers,
                LayerNorm(d_model) if normalize_before else None)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ... import ops
        import jax.numpy as jnp
        from ...core.tensor import Tensor
        mask = jnp.where(jnp.tril(jnp.ones((length, length), bool)),
                         jnp.zeros((length, length), jnp.float32),
                         jnp.full((length, length), -1e9, jnp.float32))
        return Tensor(mask)
