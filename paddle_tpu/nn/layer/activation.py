"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from ..layer_base import Layer
from .. import initializer as init_mod
from ...ops import nn_ops


def _simple(name, fn_name):
    class _Act(Layer):
        def __init__(self, name=None):
            super().__init__()

        def forward(self, x):
            return getattr(nn_ops, fn_name)(x)
    _Act.__name__ = name
    return _Act


ReLU = _simple("ReLU", "relu")
ReLU6 = _simple("ReLU6", "relu6")
Sigmoid = _simple("Sigmoid", "sigmoid")
Tanh = _simple("Tanh", "tanh")
Silu = _simple("Silu", "silu")
Swish = _simple("Swish", "swish")
Mish = _simple("Mish", "mish")
Hardswish = _simple("Hardswish", "hardswish")
Hardsigmoid = _simple("Hardsigmoid", "hardsigmoid")
Softsign = _simple("Softsign", "softsign")
Tanhshrink = _simple("Tanhshrink", "tanhshrink")
LogSigmoid = _simple("LogSigmoid", "log_sigmoid")
GLU = _simple("GLU", "glu")


class GELU(Layer):
    def __init__(self, approximate=False, name=None):
        super().__init__()
        self.approximate = approximate

    def forward(self, x):
        return nn_ops.gelu(x, self.approximate)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self.negative_slope = negative_slope

    def forward(self, x):
        return nn_ops.leaky_relu(x, self.negative_slope)


class ELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return nn_ops.elu(x, self.alpha)


class SELU(Layer):
    def __init__(self, scale=1.0507009873554805, alpha=1.6732632423543772,
                 name=None):
        super().__init__()
        self.scale, self.alpha = scale, alpha

    def forward(self, x):
        return nn_ops.selu(x, self.scale, self.alpha)


class CELU(Layer):
    def __init__(self, alpha=1.0, name=None):
        super().__init__()
        self.alpha = alpha

    def forward(self, x):
        return nn_ops.celu(x, self.alpha)


class Hardtanh(Layer):
    def __init__(self, min=-1.0, max=1.0, name=None):  # noqa: A002
        super().__init__()
        self.min, self.max = min, max

    def forward(self, x):
        return nn_ops.hardtanh(x, self.min, self.max)


class Hardshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return nn_ops.hardshrink(x, self.threshold)


class Softshrink(Layer):
    def __init__(self, threshold=0.5, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return nn_ops.softshrink(x, self.threshold)


class Softplus(Layer):
    def __init__(self, beta=1.0, threshold=20.0, name=None):
        super().__init__()
        self.beta, self.threshold = beta, threshold

    def forward(self, x):
        return nn_ops.softplus(x, self.beta, self.threshold)


class ThresholdedReLU(Layer):
    def __init__(self, threshold=1.0, name=None):
        super().__init__()
        self.threshold = threshold

    def forward(self, x):
        return nn_ops.thresholded_relu(x, self.threshold)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            (num_parameters,), attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.Constant(init))

    def forward(self, x):
        return nn_ops.prelu(x, self.weight, self._data_format)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return nn_ops.softmax(x, self.axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self.axis = axis

    def forward(self, x):
        return nn_ops.log_softmax(x, self.axis)


class Maxout(Layer):
    def __init__(self, groups, axis=1, name=None):
        super().__init__()
        self.groups, self.axis = groups, axis

    def forward(self, x):
        from ...ops import manipulation, reduction
        c = x.shape[self.axis]
        parts = manipulation.split(x, self.groups, self.axis)
        out = parts[0]
        from ...ops import math as math_ops
        for p in parts[1:]:
            out = math_ops.maximum(out, p)
        return out
