"""Convolution layers (reference: python/paddle/nn/layer/conv.py)."""
import numpy as np

from ..layer_base import Layer
from .. import initializer as init_mod
from ...ops import nn_ops


def _pair(v, n=2):
    return tuple(v) if isinstance(v, (list, tuple)) else (v,) * n


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, weight_attr, bias_attr,
                 data_format, ndim, transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _pair(kernel_size, ndim)
        self._stride = _pair(stride, ndim)
        self._padding = padding
        self._dilation = _pair(dilation, ndim)
        self._groups = groups
        self._data_format = data_format
        self._output_padding = output_padding
        if transpose:
            w_shape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            w_shape = (out_channels, in_channels // groups) + self._kernel_size
        fan_in = (in_channels // groups) * int(np.prod(self._kernel_size))
        self.weight = self.create_parameter(
            w_shape, attr=init_mod.ParamAttr._to_attr(weight_attr),
            default_initializer=init_mod.KaimingNormal(fan_in=fan_in))
        self.bias = None if bias_attr is False else self.create_parameter(
            (out_channels,), attr=init_mod.ParamAttr._to_attr(bias_attr),
            is_bias=True)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2)

    def forward(self, x):
        return nn_ops.conv2d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups,
                             self._data_format)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 1)

    def forward(self, x):
        return nn_ops.conv1d(x, self.weight, self.bias, self._stride[0],
                             self._padding, self._dilation[0], self._groups)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 3)

    def forward(self, x):
        return nn_ops.conv3d(x, self.weight, self.bias, self._stride,
                             self._padding, self._dilation, self._groups)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format="NCHW"):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, 2, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return nn_ops.conv2d_transpose(
            x, self.weight, self.bias, self._stride, self._padding,
            self._output_padding, self._dilation, self._groups)


class Conv1DTranspose(Layer):
    """Reference: nn/layer/conv.py Conv1DTranspose (weight [in, out, k])."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, int) else kernel_size[0]
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups, k), weight_attr)
        self.bias = self.create_parameter(
            (out_channels,), bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups = int(groups)

    def forward(self, x):
        return nn_ops.conv1d_transpose(x, self.weight, self.bias,
                                       self._stride, self._padding,
                                       dilation=self._dilation,
                                       groups=self._groups)


class Conv3DTranspose(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__()
        ks = (kernel_size,) * 3 if isinstance(kernel_size, int) \
            else tuple(kernel_size)
        self.weight = self.create_parameter(
            (in_channels, out_channels // groups) + ks, weight_attr)
        self.bias = self.create_parameter(
            (out_channels,), bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self._stride, self._padding, self._dilation = stride, padding, dilation
        self._groups = int(groups)

    def forward(self, x):
        return nn_ops.conv3d_transpose(x, self.weight, self.bias,
                                       self._stride, self._padding,
                                       dilation=self._dilation,
                                       groups=self._groups)
