"""paddle.nn equivalent (reference: python/paddle/nn/__init__.py)."""
from .layer_base import Layer  # noqa: F401
from .initializer import ParamAttr  # noqa: F401
from . import initializer  # noqa: F401
from . import functional  # noqa: F401

from .layer.common import (  # noqa: F401
    Linear, Dropout, Dropout2D, Embedding, Flatten, Identity, Pad2D,
    Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, PixelShuffle,
    CosineSimilarity, Bilinear, Pad1D, Pad3D, Dropout3D, AlphaDropout,
    PairwiseDistance, Unfold,
)
from .layer.conv import (  # noqa: F401
    Conv1D, Conv2D, Conv3D, Conv2DTranspose, Conv1DTranspose,
    Conv3DTranspose,
)
from .layer.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm,
    LayerNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layer.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, AvgPool1D, AvgPool2D, AdaptiveAvgPool1D,
    AdaptiveAvgPool2D, AdaptiveMaxPool2D, MaxPool3D, AvgPool3D,
    AdaptiveAvgPool3D, AdaptiveMaxPool3D, AdaptiveMaxPool1D,
)
from .layer.activation import (  # noqa: F401
    ReLU, ReLU6, Sigmoid, Tanh, Silu, Swish, Mish, Hardswish, Hardsigmoid,
    Softsign, Tanhshrink, LogSigmoid, GELU, LeakyReLU, ELU, SELU, CELU,
    Hardtanh, Hardshrink, Softshrink, Softplus, ThresholdedReLU, PReLU,
    Softmax, LogSoftmax, Maxout, GLU,
)
from .layer.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss, BCEWithLogitsLoss,
    SmoothL1Loss, KLDivLoss, MarginRankingLoss, CTCLoss, HSigmoidLoss,
)
from .layer.container import (  # noqa: F401
    Sequential, LayerList, ParameterList, LayerDict,
)
from .layer.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layer.rnn import (  # noqa: F401
    LSTM, GRU, SimpleRNN, LSTMCell, GRUCell, RNNBase, RNNCellBase,
    SimpleRNNCell, RNN, BiRNN, BeamSearchDecoder, dynamic_decode,
)
from .clip import (  # noqa: F401
    ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
)
