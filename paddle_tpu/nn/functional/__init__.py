"""paddle.nn.functional equivalent (reference: python/paddle/nn/functional/).
Mostly re-exports the primitive op library."""
from ...ops.nn_ops import (  # noqa: F401
    relu, relu6, sigmoid, tanh, silu, swish, mish, hardswish, hardsigmoid,
    softsign, tanhshrink, log_sigmoid, gelu, leaky_relu, elu, selu, celu,
    hardtanh, hardshrink, softshrink, softplus, thresholded_relu, prelu,
    softmax, log_softmax, glu,
    linear, conv2d, conv1d, conv3d, conv2d_transpose,
    max_pool2d, avg_pool2d, max_pool1d, avg_pool1d,
    adaptive_avg_pool2d, adaptive_max_pool2d,
    layer_norm, batch_norm, group_norm, instance_norm, normalize,
    local_response_norm,
    dropout, dropout2d, embedding, one_hot,
    softmax_with_cross_entropy, cross_entropy, mse_loss, l1_loss,
    smooth_l1_loss, binary_cross_entropy, binary_cross_entropy_with_logits,
    nll_loss, kl_div, square_error_cost, margin_ranking_loss,
    cosine_similarity, interpolate, upsample, pixel_shuffle, label_smooth,
    temporal_shift,
    max_pool3d, avg_pool3d, adaptive_avg_pool3d, adaptive_max_pool3d,
    adaptive_avg_pool1d, adaptive_max_pool1d, conv1d_transpose,
    conv3d_transpose, dropout3d, alpha_dropout, maxout, bilinear,
    log_loss, dice_loss, npair_loss, sigmoid_focal_loss, ctc_loss,
    hsigmoid_loss, affine_grid, grid_sample, gather_tree,
    relu_, elu_, softmax_,
)
from ...ops.math import tanh_  # noqa: F401
from ...ops.manipulation import pad, unfold  # noqa: F401
from ...ops.attention import (  # noqa: F401
    scaled_dot_product_attention, flash_attention,
)


def diag_embed(x, offset=0, dim1=-2, dim2=-1):
    import jax.numpy as jnp
    from ...core.dispatch import register_op as _r
    from ...ops.creation import _register_created
    from ...core.tensor import Tensor
    v = x.value
    n = v.shape[-1]
    out = jnp.zeros(v.shape + (n,), v.dtype)
    idx = jnp.arange(n)
    out = out.at[..., idx, idx].set(v)
    return _register_created(Tensor(out))


def sequence_mask(lengths, maxlen=None, dtype="int64"):
    """Reference: fluid.layers.sequence_mask."""
    import jax.numpy as jnp
    from ...core.tensor import Tensor
    from ...core import dtype as dtype_mod
    from ...ops.creation import _register_created
    lv = lengths.value
    if maxlen is None:
        maxlen = int(lv.max())
    row = jnp.arange(maxlen)
    mask = row[None, :] < lv[..., None]
    return _register_created(Tensor(mask.astype(dtype_mod.to_jax_dtype(dtype))))
from ...ops.sequence import (  # noqa: F401,E402
    sequence_pad, sequence_unpad, sequence_pool, sequence_softmax,
    sequence_expand, sequence_reverse,
)
