"""Parameter initializers + ParamAttr.

Reference parity: python/paddle/nn/initializer/ (Constant, Normal,
TruncatedNormal, Uniform, XavierNormal/Uniform, KaimingNormal/Uniform,
Assign) and python/paddle/fluid/param_attr.py ParamAttr. Initialization
happens host-side with the global generator's key for determinism.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core import rng as rng_mod


def _key():
    from ..core import lazy as lazy_mod
    return lazy_mod.concrete(rng_mod.next_key().value)


class Initializer:
    def __call__(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.normal(_key(), shape, jnp.float32) * self.std
                + self.mean).astype(dtype)


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype):
        return (jax.random.truncated_normal(_key(), -2.0, 2.0, shape,
                                            jnp.float32) * self.std
                + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype):
        return jax.random.uniform(_key(), shape, jnp.float32, self.low,
                                  self.high).astype(dtype)


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weight [in, out]
        return shape[0], shape[1]
    # conv OIHW
    rf = int(np.prod(shape[2:]))
    return shape[1] * rf, shape[0] * rf


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(_key(), shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(_key(), shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        std = gain / math.sqrt(fi)
        return (jax.random.normal(_key(), shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in or fi
        gain = math.sqrt(2.0 / (1 + self.negative_slope ** 2))
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(_key(), shape, jnp.float32, -limit,
                                  limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype):
        from ..core.tensor import Tensor
        v = self.value.value if isinstance(self.value, Tensor) else np.asarray(self.value)
        arr = jnp.asarray(v, dtype)
        assert tuple(arr.shape) == tuple(shape), \
            f"Assign shape mismatch {arr.shape} vs {shape}"
        return arr


class ParamAttr:
    """Reference: python/paddle/fluid/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(arg):
        if arg is None or isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, Initializer):
            return ParamAttr(initializer=arg)
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if arg is False:
            return False
        return ParamAttr()


class Bilinear(Initializer):
    """Reference: nn/initializer/Bilinear — bilinear-upsample kernel for
    transposed convs (weight [out, in, kh, kw])."""

    def __call__(self, shape, dtype):
        import numpy as np
        out_c, in_c, kh, kw = shape
        f_h, f_w = (kh + 1) // 2, (kw + 1) // 2
        ch = (2 * f_h - 1 - f_h % 2) / (2.0 * f_h)
        cw = (2 * f_w - 1 - f_w % 2) / (2.0 * f_w)
        og = np.ogrid[:kh, :kw]
        filt = ((1 - abs(og[0] / f_h - ch))
                * (1 - abs(og[1] / f_w - cw))).astype(np.float32)
        # reference BilinearInitializer writes the filter at EVERY
        # (out, in) channel pair (fluid/initializer.py flat loop)
        w = np.broadcast_to(filt, shape).copy()
        return jnp.asarray(w, dtype)


_global_initializer = [None, None]  # (weight init, bias init)


def set_global_initializer(weight_init, bias_init=None):
    """Reference: nn/initializer/set_global_initializer — default
    initializers used when a layer's attr doesn't specify one. Pass
    (None, None) to reset."""
    _global_initializer[0] = weight_init
    _global_initializer[1] = bias_init


def get_global_initializer(is_bias=False):
    return _global_initializer[1 if is_bias else 0]
