"""paddle.nn.utils (reference: python/paddle/nn/utils/__init__.py —
weight_norm / remove_weight_norm / spectral_norm hooks).

Reparameterizations run as forward-pre-hooks recomputing the layer's
weight from the stored factors each call, so the factors (not the fused
weight) are what the optimizer trains — the reference hook contract
(nn/utils/weight_norm_hook.py, spectral_norm_hook.py)."""
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Parameter
from ..core.dispatch import register_op


@register_op("weight_norm_recompose")
def _wn_recompose(g, v, *, dim, eps):
    if dim < 0:  # dim=None semantics: scalar g, whole-tensor norm
        norm = jnp.sqrt(jnp.sum(v * v) + eps)
        return v / norm * g
    axes = tuple(i for i in range(v.ndim) if i != dim)
    norm = jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True) + eps)
    shape = [1] * v.ndim
    shape[dim] = -1
    return v / norm * g.reshape(shape)


def weight_norm(layer, name="weight", dim=0):
    """w = g * v/||v|| (reference weight_norm_hook.py). Trains g and v;
    recomputes `name` before each forward."""
    w = getattr(layer, name)
    if dim is None:
        dim = -1  # reference: norm over the WHOLE tensor, scalar g
    wv = np.asarray(w.numpy())
    if dim < 0:
        g0 = np.sqrt((wv * wv).sum())
    else:
        axes = tuple(i for i in range(wv.ndim) if i != dim)
        g0 = np.sqrt((wv * wv).sum(axis=axes))
    v = Parameter(jnp.asarray(wv), name=f"{w.name}_v")
    g = Parameter(jnp.asarray(g0.astype(np.float32)),
                  name=f"{w.name}_g")
    setattr(layer, f"{name}_v", v)
    setattr(layer, f"{name}_g", g)
    # the fused weight becomes derived state, not a trained Parameter
    object.__setattr__(layer, name, None)
    layer._parameters.pop(name, None)

    def _pre_hook(lyr, inputs):
        fused = _wn_recompose(g, v, dim=int(dim), eps=1e-12)
        object.__setattr__(lyr, name, fused)
        return None

    helper = layer.register_forward_pre_hook(_pre_hook)
    layer.__dict__.setdefault("_weight_norm_hooks", {})[name] = \
        (helper, dim)
    _pre_hook(layer, None)  # materialize once for immediate inspection
    return layer


def remove_weight_norm(layer, name="weight"):
    """Fold g*v/||v|| back into a plain trained Parameter."""
    hooks = layer.__dict__.get("_weight_norm_hooks", {})
    if name not in hooks:
        raise ValueError(f"no weight_norm hook on {name!r}")
    helper, dim = hooks.pop(name)
    helper.remove()
    g = getattr(layer, f"{name}_g")
    v = getattr(layer, f"{name}_v")
    fused = _wn_recompose(g, v, dim=int(dim), eps=1e-12)
    base = v.name[:-2] if v.name.endswith("_v") else v.name
    p = Parameter(fused.value, name=base)
    setattr(layer, name, p)
    object.__setattr__(layer, f"{name}_g", None)
    object.__setattr__(layer, f"{name}_v", None)
    layer._parameters.pop(f"{name}_g", None)
    layer._parameters.pop(f"{name}_v", None)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12,
                  dim=None):
    """Divide `name` by its largest singular value each forward
    (reference spectral_norm_hook.py), reusing the SpectralNorm layer's
    power-iteration op."""
    from .layer.norm import SpectralNorm
    from .layer.common import Linear
    w = getattr(layer, name)
    if dim is None:
        # reference spectral_norm_hook.py: Linear / transposed convs
        # iterate around the OUTPUT axis (dim 1), others dim 0
        cls = type(layer).__name__
        dim = 1 if isinstance(layer, Linear) or "Transpose" in cls             else 0
    sn = SpectralNorm(list(w.shape), dim=int(dim),
                      power_iters=int(n_power_iterations), eps=float(eps))
    orig = Parameter(w.value, name=f"{w.name}_orig")
    setattr(layer, f"{name}_orig", orig)
    # attach the power-iteration state as a sublayer: its weight_u/
    # weight_v buffers then checkpoint with the host layer
    setattr(layer, f"_{name}_spectral_norm", sn)
    object.__setattr__(layer, name, None)
    layer._parameters.pop(name, None)

    def _pre_hook(lyr, inputs):
        object.__setattr__(lyr, name, sn(orig))
        return None

    helper = layer.register_forward_pre_hook(_pre_hook)
    layer.__dict__.setdefault("_spectral_norm_hooks", {})[name] = helper
    _pre_hook(layer, None)
    return layer
