"""Gradient clipping.

Reference parity: python/paddle/fluid/clip.py (ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Clippers operate on (param, grad)
lists; the optimizer applies them before the update (reference:
Optimizer._create_optimization_pass -> grad_clip).
"""
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


@register_op("clip_by_value", differentiable=False)
def _clip_by_value(g, *, mn, mx):
    return jnp.clip(g, mn, mx)


@register_op("clip_by_norm", differentiable=False)
def _clip_by_norm(g, *, clip_norm):
    n = jnp.sqrt(jnp.sum(jnp.square(g)))
    factor = jnp.where(n > clip_norm, clip_norm / jnp.maximum(n, 1e-12), 1.0)
    return g * factor.astype(g.dtype)


@register_op("global_norm_sq", differentiable=False)
def _global_norm_sq(*grads):
    total = jnp.zeros((), jnp.float32)
    for g in grads:
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return total


@register_op("global_norm_scale", differentiable=False)
def _apply_global_scale(g, norm_sq, *, clip_norm):
    norm = jnp.sqrt(norm_sq)
    factor = clip_norm / jnp.maximum(norm, clip_norm)
    return g * factor.astype(g.dtype)


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _clip_by_value(g, mn=self.min, mx=self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _clip_by_norm(g, clip_norm=self.clip_norm)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Reference: fluid/clip.py ClipGradByGlobalNorm — scales all grads by
    clip_norm/global_norm when global_norm > clip_norm."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def _global_norm_sq(self, grads):
        return _global_norm_sq(*grads)

    def __call__(self, params_grads):
        grads = [g for p, g in params_grads
                 if g is not None and getattr(p, "need_clip", True)]
        if not grads:
            return params_grads
        norm_sq = self._global_norm_sq(grads)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _apply_global_scale(g, norm_sq,
                                               clip_norm=self.clip_norm)))
        return out


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
