"""Gradient clipping.

Reference parity: python/paddle/fluid/clip.py (ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm). Clippers operate on (param, grad)
lists; the optimizer applies them before the update (reference:
Optimizer._create_optimization_pass -> grad_clip).
"""
import jax.numpy as jnp

from ..core.dispatch import register_op
from ..core.tensor import Tensor


@register_op("clip_by_value", differentiable=False)
def _clip_by_value(g, *, mn, mx):
    return jnp.clip(g, mn, mx)


@register_op("clip_by_norm", differentiable=False)
def _clip_by_norm(g, *, clip_norm):
    n = jnp.sqrt(jnp.sum(jnp.square(g)))
    factor = jnp.where(n > clip_norm, clip_norm / jnp.maximum(n, 1e-12), 1.0)
    return g * factor.astype(g.dtype)


@register_op("global_norm_sq", differentiable=False)
def _global_norm_sq(*grads):
    total = jnp.zeros((), jnp.float32)
    for g in grads:
        total = total + jnp.sum(jnp.square(g.astype(jnp.float32)))
    return total


@register_op("global_norm_scale", differentiable=False)
def _apply_global_scale(g, norm_sq, *, clip_norm):
    norm = jnp.sqrt(norm_sq)
    factor = clip_norm / jnp.maximum(norm, clip_norm)
    return g * factor.astype(g.dtype)


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = float(max)
        self.min = float(min) if min is not None else -float(max)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _clip_by_value(g, mn=self.min, mx=self.max)))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            out.append((p, _clip_by_norm(g, clip_norm=self.clip_norm)))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Reference: fluid/clip.py ClipGradByGlobalNorm — scales all grads by
    clip_norm/global_norm when global_norm > clip_norm."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = float(clip_norm)

    def _global_norm_sq(self, grads):
        return _global_norm_sq(*grads)

    @staticmethod
    def _is_sparse(g):
        from ..core.sparse_grad import SparseGradTensor
        return isinstance(g, SparseGradTensor) and g.is_sparse()

    def __call__(self, params_grads):
        from ..core.sparse_grad import SparseGradTensor
        clippable = [(p, g) for p, g in params_grads
                     if g is not None and getattr(p, "need_clip", True)]
        dense = [g for p, g in clippable if not self._is_sparse(g)]
        # coalesced copies (originals untouched — like the dense path,
        # clipping returns NEW grads and leaves param.grad as-is)
        sparse_co = {id(g): g.slices.coalesce()
                     for p, g in clippable if self._is_sparse(g)}
        if not dense and not sparse_co:
            return params_grads
        # sparse grads join the global norm through their coalesced row
        # values (zero rows contribute zero) without densifying
        norm_sq = self._global_norm_sq(dense) if dense \
            else Tensor(jnp.zeros((), jnp.float32))
        if sparse_co:
            total = norm_sq.value
            for co in sparse_co.values():
                total = total + jnp.sum(
                    jnp.square(co.values.astype(jnp.float32)))
            norm_sq = Tensor(total)
        factor = None
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, "need_clip", True):
                out.append((p, g))
                continue
            if self._is_sparse(g):
                if factor is None:
                    norm = jnp.sqrt(norm_sq.value)
                    factor = self.clip_norm / jnp.maximum(norm,
                                                          self.clip_norm)
                co = sparse_co[id(g)]
                out.append((p, SparseGradTensor(
                    co.scale(factor.astype(co.values.dtype)),
                    name=g.name)))
                continue
            out.append((p, _apply_global_scale(g, norm_sq,
                                               clip_norm=self.clip_norm)))
        return out


GradientClipByValue = ClipGradByValue
GradientClipByNorm = ClipGradByNorm
GradientClipByGlobalNorm = ClipGradByGlobalNorm
