"""framework utilities: save/load, random seed plumbing, core types.
Reference parity: python/paddle/framework/."""
from . import io_utils  # noqa: F401
from .io_utils import save, load  # noqa: F401
from ..core.tensor import Tensor, Parameter  # noqa: F401
from ..core.rng import seed  # noqa: F401
