"""paddle.save / paddle.load.

Reference parity: python/paddle/framework/io.py:550 (save) / :766 (load) —
pickle-based state_dict persistence. Tensors are converted to numpy for
serialization; nested dicts/lists preserved. bfloat16 arrays are stored as
a (uint16 bits, 'bfloat16') marker since numpy lacks the dtype natively.
"""
import os
import pickle

import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor

_BF16_TAG = "__bf16__"


def _to_picklable(obj):
    if isinstance(obj, Tensor):
        v = obj.value
        if v.dtype == jnp.bfloat16:
            return {_BF16_TAG: np.asarray(v.astype(jnp.float32))}
        return np.asarray(v)
    if isinstance(obj, jnp.ndarray):
        return np.asarray(obj)
    if isinstance(obj, dict):
        return {k: _to_picklable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_picklable(v) for v in obj)
    return obj


def _from_picklable(obj):
    if isinstance(obj, dict):
        if set(obj.keys()) == {_BF16_TAG}:
            return jnp.asarray(obj[_BF16_TAG]).astype(jnp.bfloat16)
        return {k: _from_picklable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_picklable(v) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_picklable(obj), f, protocol=protocol)


def load(path, **configs):
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_picklable(obj)
