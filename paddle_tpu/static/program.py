"""Real static-graph programs: symbolic capture, append_backward, Executor.

TPU-native replacement for the reference's ProgramDesc + C++ Executor
static mode (reference: python/paddle/fluid/framework.py Program/Block/
Variable, fluid/executor.py:916 Executor.run, fluid/backward.py:1377
append_backward, paddle/fluid/framework/executor.cc:166).

Design: under paddle.enable_static(), framework ops called on symbolic
Variables APPEND an op record to the current Program instead of
executing — the Program is a real, editable, introspectable op-list IR
(global_block().ops, op.type/inputs/outputs/attrs). Parameters stay
eagerly-initialized Tensors registered as persistable program inputs
(the startup program's job is done at creation, so running the startup
program is a no-op by construction). append_backward marks a gradient
boundary; at execution it becomes jax.grad over the interpreted forward
sub-program. Executor.run interprets the whole op list as ONE jax
function and jit-compiles it per feed signature — the reference's
op-by-op C++ interpreter becomes a single fused XLA program, which is
the TPU-idiomatic execution of a static graph.
"""
import threading

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dtype as dtype_mod

_state = threading.local()


def _register_with_dispatch():
    from ..core import dispatch
    dispatch._static_variable_cls = Variable
    # full method surface on symbolic variables (reference:
    # fluid/layers/math_op_patch.py monkey_patch_variable)
    from .. import ops as ops_mod
    ops_mod.patch_symbolic(Variable)


def building_program():
    """The Program currently capturing ops, or None (eager)."""
    return getattr(_state, "program", None)


def _set_building(prog):
    _state.program = prog
    # flip the dispatcher's fast-path gate. NOTE: the gate is
    # process-wide while the build state is thread-local: concurrent
    # static building from multiple threads is not supported (same as
    # the reference's global default-program state)
    from ..core import dispatch
    dispatch._static_active = prog is not None


class Variable:
    """Symbolic program variable (reference: framework.py Variable over
    VarDesc). Holds metadata only; values exist at Executor.run time."""

    __slots__ = ("name", "_shape", "_dtype", "program", "stop_gradient",
                 "persistable")

    def __init__(self, name, shape, dtype, program, stop_gradient=True):
        self.name = name
        self._shape = tuple(shape)
        self._dtype = jnp.dtype(dtype)
        self.program = program
        self.stop_gradient = stop_gradient
        self.persistable = False

    @property
    def shape(self):
        return list(self._shape)

    def aval_shape(self):
        return self._shape

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def dtype(self):
        return dtype_mod.to_paddle_dtype(self._dtype)

    @property
    def value(self):
        # static-apply recording (optimizer _apply_one reuse): reading a
        # Variable's "value" during program building yields the Variable
        # itself so `p.value = new_p.value` routes through the setter
        if building_program() is not None:
            return self
        raise RuntimeError(
            f"Variable {self.name!r} has no value outside Executor.run; "
            "fetch it via fetch_list")

    def numpy(self):
        raise RuntimeError(
            f"Variable {self.name!r} is symbolic; run the program and "
            "fetch it to get values")

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={list(self._shape)}, "
                f"dtype={self._dtype.name})")

    # arithmetic sugar routes through the regular op layer, which records
    def _binop(self, other, fn, reverse=False):
        a, b = (other, self) if reverse else (self, other)
        return fn(a, b)

    def __add__(self, o):
        from ..ops import math
        return self._binop(o, math.add)

    __radd__ = __add__

    def __sub__(self, o):
        from ..ops import math
        return self._binop(o, math.subtract)

    def __rsub__(self, o):
        from ..ops import math
        return self._binop(o, math.subtract, reverse=True)

    def __mul__(self, o):
        from ..ops import math
        return self._binop(o, math.multiply)

    __rmul__ = __mul__

    def __truediv__(self, o):
        from ..ops import math
        return self._binop(o, math.divide)

    def __rtruediv__(self, o):
        from ..ops import math
        return self._binop(o, math.divide, reverse=True)

    def __pow__(self, o):
        from ..ops import math
        return self._binop(o, math.pow)

    def __neg__(self):
        from ..ops import math
        return math.scale(self, -1.0)

    def __matmul__(self, o):
        from ..ops import math
        return self._binop(o, math.matmul)


class OpRecord:
    """One recorded op (reference: OpDesc). in_refs entries are Variable
    names (str), ("#const", array) or None; writebacks map output index ->
    persistable Tensor updated in place by this op (optimizer updates)."""

    __slots__ = ("op", "in_refs", "out_names", "attrs", "writebacks",
                 "cast")

    def __init__(self, op, in_refs, out_names, attrs, cast=None):
        self.op = op
        self.in_refs = in_refs
        self.out_names = out_names
        self.attrs = attrs
        self.writebacks = {}
        # AMP: cast float inputs to this dtype before the kernel (the
        # autocast list active when the op was recorded)
        self.cast = cast

    @property
    def type(self):
        return self.op.name

    def input_names(self):
        return [r for r in self.in_refs if isinstance(r, str)]

    def output_names(self):
        return list(self.out_names)

    def __repr__(self):
        ins = [r if isinstance(r, str)
               else ("<const>" if r is not None else "None")
               for r in self.in_refs]
        return f"{{{self.type}: ({', '.join(ins)}) -> {self.out_names}}}"


class ConstRecord:
    """A materialized constant bound to a program variable (the symbolic
    form of fill_constant — the reference records a fill_constant op)."""

    __slots__ = ("name", "array")
    type = "fill_constant"

    def __init__(self, name, array):
        self.name = name
        self.array = array

    def __repr__(self):
        return f"{{fill_constant -> {self.name}}}"


class AliasRecord:
    """env[dst] = env[src]: the fluid in-place contract (increment
    in_place=True, less_than(cond=...), assign(output=...)) expressed
    functionally — a later read of dst sees src's value."""

    __slots__ = ("src", "dst")
    type = "@alias"

    def __init__(self, src, dst):
        self.src = src
        self.dst = dst

    def __repr__(self):
        return f"{{@alias: {self.src} -> {self.dst}}}"


class WhileRecord:
    """fluid.layers.While sub-block (reference: control_flow.py:973
    While -> while_op over a sub-block ProgramDesc). TPU-native: the
    captured body records replay inside ONE lax.while_loop; the loop
    state is exactly the pre-existing variables the body aliases into
    (cond + increment/assign targets). Reverse-mode AD through a While
    is not supported (lax.while_loop limitation) — train with StaticRNN
    (lax.scan) instead."""

    __slots__ = ("cond_name", "body", "carry_names")
    type = "while"

    def __init__(self, cond_name, body, carry_names):
        self.cond_name = cond_name
        self.body = body
        self.carry_names = carry_names

    def __repr__(self):
        return (f"{{while[{self.cond_name}]: {len(self.body)} body ops, "
                f"carry {self.carry_names}}}")


class ScanRecord:
    """fluid.layers.StaticRNN sub-block (reference: control_flow.py:451
    StaticRNN -> recurrent_op). TPU-native: lax.scan over the sequence
    axis — memories are the carry, step inputs are xs, step outputs are
    stacked ys; fully reverse-differentiable, so append_backward trains
    through it."""

    __slots__ = ("body", "seq_inputs", "mems", "out_pairs")
    type = "recurrent"

    def __init__(self, body, seq_inputs, mems, out_pairs):
        self.body = body
        # list of (placeholder_name, source_seq_name)
        self.seq_inputs = seq_inputs
        # list of (mem_name, init_spec, updated_name); init_spec is a
        # source var name, or ("zeros", shape, value) with -1 batch dims
        # resolved from the sequence batch at run time
        self.mems = mems
        # list of (body_out_name, program_out_name)
        self.out_pairs = out_pairs

    def __repr__(self):
        return (f"{{recurrent: {len(self.body)} body ops, "
                f"xs {self.seq_inputs}, mems {self.mems}}}")


class GradRecord:
    """Gradient boundary (reference: the grad-op chain append_backward
    inserts). At run time: jax.grad of the interpreted forward
    sub-program wrt the listed persistable params."""

    __slots__ = ("loss_name", "params", "grad_names", "upto")

    type = "@grad"

    def __init__(self, loss_name, params, grad_names, upto):
        self.loss_name = loss_name
        self.params = params  # list of persistable Tensors
        self.grad_names = grad_names
        self.upto = upto  # number of forward records to differentiate

    def __repr__(self):
        return (f"{{@grad: d{self.loss_name}/d["
                f"{', '.join(p.name for p in self.params)}]}}")


class Program:
    """An editable op-list program (reference: framework.py Program;
    single-block subset — control flow uses lax primitives inside ops)."""

    def __init__(self):
        self.ops = []
        self.vars = {}
        self.persist = {}    # name -> Tensor (parameters, optimizer state)
        self.feed_names = []
        self._counter = [0]
        self._layer_cache = {}  # static.nn name -> layer (per program)
        self.random_seed = None

    # -- building ---------------------------------------------------------
    def _new_name(self, hint):
        self._counter[0] += 1
        return f"{hint}.tmp_{self._counter[0]}"

    def data(self, name, shape, dtype="float32"):
        shape = [(-1 if s is None else int(s)) for s in shape]
        v = Variable(name, shape, dtype_mod.to_jax_dtype(dtype), self)
        self.vars[name] = v
        if name not in self.feed_names:
            self.feed_names.append(name)
        return v

    def register_persist(self, tensor):
        if tensor.name not in self.persist:
            self.persist[tensor.name] = tensor
        return tensor.name

    def const_var(self, array, hint="fill_constant"):
        """Record a constant-producing op and return its Variable (the
        symbolic fill_constant the fluid While pattern builds loop
        state from)."""
        array = jnp.asarray(array)
        name = self._new_name(hint)
        v = Variable(name, array.shape, array.dtype, self)
        self.vars[name] = v
        self.ops.append(ConstRecord(name, array))
        return v

    def placeholder_var(self, shape, dtype, hint):
        """A named variable bound at run time by an enclosing control-
        flow record (StaticRNN step inputs / memories)."""
        name = self._new_name(hint)
        v = Variable(name, shape, dtype, self)
        self.vars[name] = v
        return v

    def alias(self, src_var, dst_var):
        """Record fluid in-place semantics: dst reads as src from here
        on (increment in_place / less_than(cond=...) / assign(output))."""
        self.ops.append(AliasRecord(src_var.name, dst_var.name))
        return dst_var

    def append_op(self, op, args, attrs, cast_dtype=None):
        """Called from Op.__call__ when building: records instead of
        executing; infers output shapes via jax.eval_shape."""
        in_refs = []
        avals = []
        for a in args:
            if isinstance(a, Variable):
                in_refs.append(a.name)
                shape = tuple(1 if s == -1 else s for s in a._shape)
                avals.append(jax.ShapeDtypeStruct(shape, a._dtype))
            elif isinstance(a, Tensor):
                name = self.register_persist(a)
                in_refs.append(name)
                avals.append(jax.ShapeDtypeStruct(
                    tuple(a.aval_shape()), a._value.dtype))
            elif a is None:
                in_refs.append(None)
                avals.append(None)
            else:
                arr = a if isinstance(a, jax.Array) else jnp.asarray(a)
                in_refs.append(("#const", arr))
                avals.append(jax.ShapeDtypeStruct(arr.shape, arr.dtype))

        def shape_fn(*arrs):
            return op.fn(*[_maybe_cast(a, cast_dtype) for a in arrs],
                         **attrs)

        zeros = [None if av is None else jnp.zeros(av.shape, av.dtype)
                 for av in avals]
        outs = jax.eval_shape(shape_fn, *zeros)
        multi = isinstance(outs, (tuple, list))
        out_list = list(outs) if multi else [outs]
        out_vars = []
        out_names = []
        for o in out_list:
            name = self._new_name(op.name)
            v = Variable(name, o.shape, o.dtype, self, stop_gradient=False)
            self.vars[name] = v
            out_names.append(name)
            out_vars.append(v)
        self.ops.append(OpRecord(op, in_refs, out_names, dict(attrs),
                                 cast=cast_dtype))
        return tuple(out_vars) if multi else out_vars[0]

    def mark_writeback(self, out_var, target_tensor):
        """The most recent producer of out_var updates target_tensor in
        place at run time (optimizer update semantics)."""
        for rec in reversed(self.ops):
            if isinstance(rec, OpRecord) and out_var.name in rec.out_names:
                idx = rec.out_names.index(out_var.name)
                rec.writebacks[idx] = target_tensor
                self.register_persist(target_tensor)
                return
        raise ValueError(f"no producer for {out_var.name}")

    def append_backward(self, loss, parameter_list=None):
        """Reference: fluid/backward.py:1377. Returns [(param, grad_var)].
        The gradient is taken of the forward sub-program recorded so far."""
        if not isinstance(loss, Variable):
            raise TypeError("append_backward needs a program Variable loss")
        params = parameter_list
        if params is None:
            params = [t for t in self.persist.values()
                      if getattr(t, "trainable", True)
                      and not t.stop_gradient]
        grad_names = []
        for p in params:
            gname = p.name + "@GRAD"
            gv = Variable(gname, tuple(p.aval_shape()),
                          p._value.dtype, self)
            self.vars[gname] = gv
            grad_names.append(gname)
        self.ops.append(GradRecord(loss.name, list(params), grad_names,
                                   len(self.ops)))
        return [(p, self.vars[g]) for p, g in zip(params, grad_names)]

    # -- introspection ----------------------------------------------------
    def global_block(self):
        return self

    def all_parameters(self):
        return list(self.persist.values())

    def clone(self, for_test=False):
        c = Program()
        c.ops = list(self.ops)
        c.vars = dict(self.vars)
        c.persist = dict(self.persist)
        c.feed_names = list(self.feed_names)
        c._counter = self._counter
        c._layer_cache = self._layer_cache
        if for_test:
            # Reference semantics (framework.py Program.clone): prune the
            # backward + optimize sub-graph — everything from the first
            # gradient boundary on — and strip state write-backs (e.g.
            # BatchNorm running stats) while KEEPING those forward ops'
            # outputs for downstream consumers.
            def strip(recs):
                out = []
                for r in recs:
                    if getattr(r, "writebacks", None):
                        out.append(OpRecord(r.op, r.in_refs, r.out_names,
                                            r.attrs, cast=r.cast))
                    elif isinstance(r, WhileRecord):
                        # writebacks can hide INSIDE sub-block bodies
                        # (e.g. batch-norm running stats updated in a
                        # StaticRNN step): a test-mode clone must not
                        # mutate persistent state from nested ops either
                        out.append(WhileRecord(r.cond_name,
                                               strip(r.body),
                                               r.carry_names))
                    elif isinstance(r, ScanRecord):
                        out.append(ScanRecord(strip(r.body),
                                              r.seq_inputs, r.mems,
                                              r.out_pairs))
                    else:
                        out.append(r)
                return out

            fwd = []
            for r in c.ops:
                if isinstance(r, GradRecord):
                    break
                fwd.append(r)
            c.ops = strip(fwd)
        return c

    def to_string(self, throw_on_error=False, with_details=False):
        lines = [f"Program(ops={len(self.ops)}, "
                 f"feeds={self.feed_names}, "
                 f"persist={list(self.persist)})"]
        lines += [f"  {rec!r}" for rec in self.ops]
        return "\n".join(lines)

    __str__ = to_string

    def _version(self):
        """Content-sensitive fingerprint so Executor caches survive only
        while the (editable) op list is truly unchanged: op identities
        catch append/delete/replace, attr reprs catch in-place edits."""
        return hash((tuple(id(r) for r in self.ops),
                     tuple(repr(getattr(r, "attrs", None))
                           for r in self.ops)))


class program_guard:
    """Reference: static.program_guard — redirects building to the given
    programs."""

    def __init__(self, main_program=None, startup_program=None):
        self.main = main_program if main_program is not None else Program()
        self.startup = startup_program

    def __enter__(self):
        self._saved = building_program()
        _set_building(self.main)
        return self

    def __exit__(self, *exc):
        _set_building(self._saved)
        return False


def _maybe_cast(a, cast_dtype):
    if cast_dtype is not None and a is not None \
            and jnp.issubdtype(a.dtype, jnp.floating):
        return a.astype(cast_dtype)
    return a


def _interpret(records, env, persist_written):
    """Execute op records over an env of name -> array."""
    for rec in records:
        if isinstance(rec, ConstRecord):
            env[rec.name] = rec.array
            continue
        if isinstance(rec, AliasRecord):
            env[rec.dst] = env[rec.src]
            continue
        if isinstance(rec, WhileRecord):
            names = list(rec.carry_names)
            cidx = names.index(rec.cond_name)

            def w_cond(carry):
                return jnp.reshape(carry[cidx], ()).astype(bool)

            def w_body(carry):
                env2 = dict(env)
                env2.update(zip(names, carry))
                _interpret(rec.body, env2, persist_written)
                return tuple(env2[n] for n in names)

            final = jax.lax.while_loop(w_cond, w_body,
                                       tuple(env[n] for n in names))
            env.update(zip(names, final))
            continue
        if isinstance(rec, ScanRecord):
            xs = tuple(env[src] for _, src in rec.seq_inputs)
            batch = xs[0].shape[1] if xs and xs[0].ndim > 1 else 1
            init = []
            for _, spec, _ in rec.mems:
                if isinstance(spec, str):
                    init.append(env[spec])
                else:
                    _, shape, value, dt = spec
                    shape = tuple(batch if s in (-1, None) else int(s)
                                  for s in shape)
                    init.append(jnp.full(shape, value, dt))
            ph_names = [ph for ph, _ in rec.seq_inputs]
            mem_names = [m for m, _, _ in rec.mems]
            new_names = [n for _, _, n in rec.mems]
            out_names = [o for o, _ in rec.out_pairs]

            def s_body(carry, xts):
                env2 = dict(env)
                env2.update(zip(mem_names, carry))
                env2.update(zip(ph_names, xts))
                _interpret(rec.body, env2, persist_written)
                return (tuple(env2[n] for n in new_names),
                        tuple(env2[o] for o in out_names))

            _, ys = jax.lax.scan(s_body, tuple(init), xs)
            for (_, prog_out), y in zip(rec.out_pairs, ys):
                env[prog_out] = y
            continue
        if isinstance(rec, GradRecord):
            pnames = [p.name for p in rec.params]

            def fwd(pvals):
                env2 = dict(env)
                env2.update(zip(pnames, pvals))
                _run_forward(rec_slice(records, rec), env2)
                return env2[rec.loss_name]

            grads = jax.grad(fwd)([env[n] for n in pnames])
            env.update(zip(rec.grad_names, grads))
            continue
        ins = []
        for r in rec.in_refs:
            if r is None:
                ins.append(None)
            elif isinstance(r, str):
                ins.append(_maybe_cast(env[r], rec.cast))
            else:
                ins.append(_maybe_cast(r[1], rec.cast))
        outs = rec.op.fn(*ins, **rec.attrs)
        out_list = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        for name, o in zip(rec.out_names, out_list):
            env[name] = o
        for idx, target in rec.writebacks.items():
            env[target.name] = out_list[idx]
            persist_written.add(target.name)


def rec_slice(records, grad_rec):
    return records[:grad_rec.upto]


def _run_forward(records, env):
    sink = set()
    _interpret([r for r in records if not isinstance(r, GradRecord)],
               env, sink)


class Executor:
    """Reference: fluid/executor.py:916. run() interprets the program as
    one jax function, jit-compiled per feed signature; persistable state
    (params, optimizer moments) is threaded through and written back, so
    consecutive run() calls train."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None,
            return_numpy=True):
        from . import _default_startup
        feed = feed or {}
        # legacy paths: python callables and the facade startup program
        if callable(program):
            out = program(**feed)
            return out if isinstance(out, (list, tuple)) else [out]
        if program is None or getattr(program, "ops", None) is None \
                or (isinstance(program, Program) and not program.ops):
            return []  # startup: params are initialized eagerly already
        if not isinstance(program, Program):
            raise TypeError(f"cannot run {type(program).__name__}")

        fetch_list = fetch_list or []
        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]
        feed_arrays = {}
        for name, val in feed.items():
            if isinstance(val, Tensor):
                val = val.value
            feed_arrays[name] = jnp.asarray(val)
        # the Program object itself keys the cache (identity hash) — and
        # the strong reference pins it, so a GC'd program's id can never
        # alias a new one; _version() invalidates on edits
        sig = (program, program._version(),
               tuple(sorted((n, tuple(a.shape), str(a.dtype))
                            for n, a in feed_arrays.items())),
               tuple(fetch_names))
        compiled = self._cache.get(sig)
        if compiled is None:
            compiled = self._compile(program, fetch_names)
            self._cache[sig] = compiled
        persist_names, jitted = compiled
        persist_vals = [program.persist[n]._value for n in persist_names]
        fetches, new_persist = jitted(feed_arrays, persist_vals)
        for n, v in zip(persist_names, new_persist):
            program.persist[n]._value = v
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    def _compile(self, program, fetch_names):
        records = list(program.ops)
        persist_names = list(program.persist)

        def run_fn(feed_arrays, persist_vals):
            env = dict(feed_arrays)
            env.update(zip(persist_names, persist_vals))
            sink = set()
            _interpret(records, env, sink)
            return ([env[n] for n in fetch_names],
                    [env[n] for n in persist_names])

        return persist_names, jax.jit(run_fn)

    def close(self):
        self._cache.clear()


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None):
    """Module-level API (reference: paddle.static.append_backward)."""
    prog = loss.program if isinstance(loss, Variable) \
        else building_program()
    if prog is None:
        raise RuntimeError("append_backward requires static mode")
    return prog.append_backward(loss, parameter_list)


# -- program serialization (reference: save_inference_model writing
# ProgramDesc protobuf + persistables, fluid/io.py:668; here the op-list
# IR serializes by op NAME — ops rebind from the registry at load) --------

def _serialize_record(rec):
    if isinstance(rec, GradRecord):
        return {"kind": "grad", "loss": rec.loss_name,
                "params": [p.name for p in rec.params],
                "grad_names": list(rec.grad_names),
                "upto": rec.upto}
    if isinstance(rec, ConstRecord):
        return {"kind": "const", "name": rec.name,
                "array": np.asarray(rec.array)}
    if isinstance(rec, AliasRecord):
        return {"kind": "alias", "src": rec.src, "dst": rec.dst}
    if isinstance(rec, WhileRecord):
        return {"kind": "while", "cond": rec.cond_name,
                "body": [_serialize_record(r) for r in rec.body],
                "carry": list(rec.carry_names)}
    if isinstance(rec, ScanRecord):
        return {"kind": "scan",
                "body": [_serialize_record(r) for r in rec.body],
                "seq_inputs": list(rec.seq_inputs),
                "mems": list(rec.mems),
                "out_pairs": list(rec.out_pairs)}
    return {
        "kind": "op", "type": rec.op.name,
        "in_refs": [r if (r is None or isinstance(r, str))
                    else ("#const", np.asarray(r[1]))
                    for r in rec.in_refs],
        "out_names": list(rec.out_names),
        "attrs": rec.attrs,
        "cast": None if rec.cast is None
        else np.dtype(rec.cast).name,
        "writebacks": {i: t.name
                       for i, t in rec.writebacks.items()},
    }


def _serialize_program(program):
    recs = [_serialize_record(rec) for rec in program.ops]
    var_meta = {n: (list(v._shape), v._dtype.name, v.stop_gradient)
                for n, v in program.vars.items()}
    persist = {n: (np.asarray(t._value),
                   bool(getattr(t, "trainable", True)),
                   bool(t.stop_gradient))
               for n, t in program.persist.items()}
    return {"records": recs, "vars": var_meta, "persist": persist,
            "feed_names": list(program.feed_names),
            "counter": program._counter[0]}


def _deserialize_record(r, prog):
    from ..core.dispatch import _REGISTRY
    kind = r["kind"]
    if kind == "grad":
        return GradRecord(
            r["loss"], [prog.persist[p] for p in r["params"]],
            list(r["grad_names"]), int(r["upto"]))
    if kind == "const":
        return ConstRecord(r["name"], jnp.asarray(r["array"]))
    if kind == "alias":
        return AliasRecord(r["src"], r["dst"])
    if kind == "while":
        return WhileRecord(r["cond"],
                           [_deserialize_record(b, prog)
                            for b in r["body"]],
                           list(r["carry"]))
    if kind == "scan":
        return ScanRecord([_deserialize_record(b, prog)
                           for b in r["body"]],
                          [tuple(p) for p in r["seq_inputs"]],
                          [tuple(m) for m in r["mems"]],
                          [tuple(p) for p in r["out_pairs"]])
    op = _REGISTRY.get(r["type"])
    if op is None:
        raise ValueError(
            f"program references unknown op {r['type']!r}; is the "
            "op registered in this build?")
    rec = OpRecord(op,
                   [x if (x is None or isinstance(x, str))
                    else ("#const", jnp.asarray(x[1]))
                    for x in r["in_refs"]],
                   list(r["out_names"]), dict(r["attrs"]),
                   cast=None if r.get("cast") is None
                   else jnp.dtype(r["cast"]))
    rec.writebacks = {int(i): prog.persist[name]
                      for i, name in r["writebacks"].items()}
    return rec


def _deserialize_program(blob):
    prog = Program()
    prog.feed_names = list(blob["feed_names"])
    prog._counter = [int(blob.get("counter", 0))]
    for n, (shape, dtype, stop_grad) in blob["vars"].items():
        prog.vars[n] = Variable(n, shape, np.dtype(dtype), prog,
                                stop_gradient=stop_grad)
    for n, (arr, trainable, stop_grad) in blob["persist"].items():
        t = Tensor(arr, name=n, persistable=True,
                   stop_gradient=stop_grad)
        t.trainable = trainable
        prog.persist[n] = t
    for r in blob["records"]:
        prog.ops.append(_deserialize_record(r, prog))
    return prog


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """Reference: paddle.static.save_inference_model — persists the
    PRUNED (forward-only) program plus its persistables; feed/fetch var
    names are recorded so load restores the serving contract."""
    import pickle
    if program is None:
        program = building_program()
    if program is None:
        raise RuntimeError("no program to save")
    pruned = program.clone(for_test=True)
    blob = _serialize_program(pruned)
    blob["feed_targets"] = [v.name if isinstance(v, Variable) else str(v)
                            for v in (feed_vars or [])]
    blob["fetch_targets"] = [v.name if isinstance(v, Variable) else str(v)
                             for v in (fetch_vars or [])]
    with open(str(path_prefix) + ".pdmodel", "wb") as f:
        pickle.dump(blob, f, protocol=4)
    return str(path_prefix) + ".pdmodel"


def load_inference_model(path_prefix, executor=None, **kwargs):
    """Reference: paddle.static.load_inference_model — returns
    (program, feed_target_names, fetch_targets)."""
    import pickle
    path = str(path_prefix)
    if not path.endswith(".pdmodel"):
        path += ".pdmodel"
    with open(path, "rb") as f:
        blob = pickle.load(f)
    prog = _deserialize_program(blob)
    fetch = [prog.vars[n] for n in blob.get("fetch_targets", [])]
    return prog, list(blob.get("feed_targets", [])), fetch


_register_with_dispatch()
