"""InputSpec (reference: python/paddle/static/input.py InputSpec)."""


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None):
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.name = name

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.aval_shape()), str(tensor.value.dtype),
                   name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, str(ndarray.dtype), name)

    def batch(self, batch_size):
        self.shape = (batch_size,) + tuple(self.shape)
        return self

    def unbatch(self):
        self.shape = tuple(self.shape[1:])
        return self
