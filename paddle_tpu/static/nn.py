"""Control-flow ops: cond / while_loop / switch_case / case.

Reference parity: paddle/fluid/operators/controlflow/
(conditional_block_op.cc, while_op.cc executing sub-block programs) and
python/paddle/fluid/layers/control_flow.py (cond, while_loop,
switch_case, case).

TPU-native design: the reference interprets sub-block ProgramDescs; here
the branches/bodies are python callables lowered to lax.cond /
lax.while_loop / lax.switch, so under to_static the control flow compiles
into the XLA program (data-dependent branching on device, no host sync),
and in eager mode it still executes correctly (jax primitives work
outside jit too).
"""
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import trace as trace_mod
from ..core.lazy import LazyArray as _LazyArray


def _to_arr(v):
    """Tensor/LazyArray/python value -> raw jax array. Deferred lazy
    values MUST materialize here: a LazyArray is a registered pytree
    CustomNode, so one reaching a lax.cond branch output (e.g. an
    identity branch returning a captured not-yet-flushed tensor) makes
    the two branch structures unequal."""
    if isinstance(v, Tensor):
        v = v.value  # trace-aware: notifies the active TraceContext
    if isinstance(v, _LazyArray):
        return v.materialize()
    return v if isinstance(v, jax.Array) else jnp.asarray(v)


def _wrap_out(tree):
    """Wrap raw arrays into Tensors, REGISTERED with the active trace:
    an unregistered Tensor read later in the same record pass would be
    captured as an external input holding a trace-local value (a leaked
    tracer at replay time)."""
    if isinstance(tree, (tuple, list)):
        return type(tree)(_wrap_out(t) for t in tree)
    t = Tensor(tree)
    ctx = trace_mod.current_trace()
    if ctx is not None:
        ctx.register_created(t)
    return t


def _lift(fn, label="subtrace"):
    """Make a user callable operate on raw arrays: Tensor-in, array-out.

    ``label`` names the lax sub-trace this callable is lowered under
    (while_cond / while_body / cond branches). Under an active trace
    the body runs inside an analysis sub-trace scope: with birth
    tracking enabled (paddle_tpu.analysis), values born here that
    escape into the outer trace are reported as structured
    TracerLeakErrors at scope exit; with it disabled the scope is a
    shared no-op."""
    def lifted(*arrays):
        ctx = trace_mod.current_trace()

        def run():
            ins = [Tensor(a) for a in arrays]
            if ctx is not None:
                for t in ins:
                    ctx.register_created(t)
            out = fn(*ins) if arrays else fn()
            return jax.tree.map(_to_arr, out,
                               is_leaf=lambda x: isinstance(x, Tensor))
        if ctx is not None:
            from ..analysis import birth as _birth
            with _birth.subtrace(label):
                return run()
        # eager call sites still trace through lax primitives fine
        with trace_mod.trace_guard(trace_mod.TraceContext("jit")):
            return run()
    return lifted


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Reference: control_flow.py cond → conditional_block ops; here
    lax.cond — both branches compile, the predicate selects on device."""
    p = _to_arr(pred).astype(bool).reshape(())
    out = jax.lax.cond(p, _lift(true_fn, "cond_true"),
                       _lift(false_fn, "cond_false"))
    return _wrap_out(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    """Reference: control_flow.py while_loop → while_op sub-block; here
    lax.while_loop over the carried loop_vars pytree."""
    init = [jax.tree.map(_to_arr, v,
                         is_leaf=lambda x: isinstance(x, Tensor))
            for v in loop_vars]

    def _cond(carry):
        out = _lift(cond_fn, "while_cond")(*carry)
        return _to_arr(out).astype(bool).reshape(())

    def _body(carry):
        out = _lift(body_fn, "while_body")(*carry)
        out = out if isinstance(out, (tuple, list)) else (out,)
        return tuple(out)

    final = jax.lax.while_loop(_cond, _body, tuple(init))
    return [_wrap_out(v) for v in final]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Reference: control_flow.py switch_case; here lax.switch. branch_fns
    may be a list of callables or (index, callable) pairs."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [_lift(f, f"switch_branch{i}") for i, (_, f) in enumerate(items)]
    if default is not None:
        fns.append(_lift(default, "switch_default"))
        default_idx = len(fns) - 1
    else:
        default_idx = len(fns) - 1  # reference: last branch is default
    idx = _to_arr(branch_index).astype(jnp.int32).reshape(())
    # map branch_index -> position in fns (default when no key matches)
    pos = jnp.full((), default_idx, jnp.int32)
    for i, k in enumerate(keys):
        pos = jnp.where(idx == k, i, pos)
    out = jax.lax.switch(pos, fns)
    return _wrap_out(out)


def case(pred_fn_pairs, default=None, name=None):
    """Reference: control_flow.py case — first true predicate wins."""
    preds = [_to_arr(p).astype(bool).reshape(()) for p, _ in pred_fn_pairs]
    fns = [_lift(f, f"case_branch{i}")
           for i, (_, f) in enumerate(pred_fn_pairs)]
    if default is not None:
        fns.append(_lift(default, "case_default"))
    else:
        fns.append(fns[-1])
    # index of first true predicate, else default slot
    stacked = jnp.stack(preds)
    first = jnp.argmax(stacked)
    has_true = jnp.any(stacked)
    pos = jnp.where(has_true, first, len(fns) - 1).astype(jnp.int32)
    out = jax.lax.switch(pos, fns)
    return _wrap_out(out)


def fc(x, size, num_flatten_dims=1, activation=None, name=None,
       weight_attr=None, bias_attr=None):
    """Reference: paddle.static.nn.fc. Every unnamed call creates fresh
    parameters (reference: unique auto-generated param names per
    append_op); a `name` reuses that layer's parameters WITHIN the same
    program only (so separate Programs never share weights)."""
    from ..nn.layer.common import Linear
    from ..ops import nn_ops as _F
    from ..ops.nn_ops import fc_flatten
    from .program import building_program
    x, in_dim = fc_flatten(x, num_flatten_dims)
    prog = building_program()
    cache = prog._layer_cache if prog is not None else {}
    key = ("fc", name, in_dim, int(size)) if name is not None else None
    layer = cache.get(key) if key is not None else None
    if layer is None:
        layer = Linear(in_dim, int(size), weight_attr=weight_attr,
                       bias_attr=bias_attr)
        if key is not None:
            cache[key] = layer
    out = layer(x)
    if activation:
        act = getattr(_F, activation, None)
        if act is None:
            raise ValueError(f"unknown activation {activation!r}")
        out = act(out)
    return out


# ---- fluid-layer forwards (reference: paddle/static/nn/__init__.py
# __all__ — the static op-assembly API IS the fluid.layers surface).
# Lazily resolved via PEP 562 to avoid a circular import (fluid.layers
# imports static.data at module load).

_FLUID_FORWARDS = (
    "batch_norm", "embedding", "bilinear_tensor_product", "conv2d",
    "conv2d_transpose", "conv3d", "conv3d_transpose", "crf_decoding",
    "data_norm", "group_norm", "instance_norm",
    "layer_norm", "multi_box_head", "nce", "prelu", "py_func",
    "row_conv", "spectral_norm", "sequence_conv", "sequence_softmax",
    "sequence_pool", "sequence_concat", "sequence_first_step",
    "sequence_last_step", "sequence_slice", "sequence_expand",
    "sequence_expand_as", "sequence_pad", "sequence_unpad",
    "sequence_reshape", "sequence_scatter", "sequence_enumerate",
    "sequence_reverse",
)


def __getattr__(name):
    if name in _FLUID_FORWARDS:
        from ..fluid import layers as _fl
        return getattr(_fl, name)
    if name == "deform_conv2d":
        from ..fluid import layers as _fl
        return _fl.deformable_conv
    if name == "sparse_embedding":
        from ..fluid import layers as _fl

        def sparse_embedding(input, size, **kw):  # noqa: A002
            kw.setdefault("is_sparse", True)
            return _fl.embedding(input, size, **kw)
        return sparse_embedding
    raise AttributeError(f"module 'paddle.static.nn' has no attribute "
                         f"{name!r}")


def __dir__():
    return sorted(set(globals()) | set(_FLUID_FORWARDS)
                  | {"sparse_embedding", "deform_conv2d"})
