"""paddle.static equivalent (functional subset).

Reference parity: python/paddle/static/ (InputSpec, Program/Executor,
program_guard). The reference's static graph is a ProgramDesc interpreted
by the C++ Executor (executor.cc:166); the TPU-native equivalent of a
static program is a traced-and-compiled XLA computation (jit.to_static).
This module provides InputSpec plus a thin Program/Executor facade over
the trace machinery so `paddle.static`-style code has a migration path;
new code should use paddle_tpu.jit.to_static directly.
"""
from .input_spec import InputSpec  # noqa: F401

_static_mode = [False]


def _enable():
    _static_mode[0] = True


class Program:
    """Facade: holds a python callable captured via to_static."""

    def __init__(self, fn=None):
        self.fn = fn

    def clone(self, for_test=False):
        return Program(self.fn)


_default_main = Program()
_default_startup = Program()


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


class Executor:
    """Facade over direct eager/compiled execution. `run(fn, feed, fetch)`
    executes a python function (the 'program')."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None):
        if callable(program):
            out = program(**(feed or {}))
        elif isinstance(program, Program) and callable(program.fn):
            out = program.fn(**(feed or {}))
        else:
            raise TypeError(
                "paddle_tpu.static.Executor runs python callables; build "
                "models with nn.Layer + jit.to_static instead of op-desc "
                "programs")
        return out if isinstance(out, (list, tuple)) else [out]


def data(name, shape, dtype="float32", lod_level=0):
    return InputSpec(shape, dtype, name)


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


from ..amp import auto_cast as amp  # noqa: F401,E402
from . import nn  # noqa: F401,E402
