"""paddle.static: real program-capture static graph mode.

Reference parity: python/paddle/static/ (Program/Executor/program_guard/
data/append_backward, fluid/executor.py:916, fluid/backward.py:1377).
See program.py for the TPU-native design: ops record into an editable
op-list Program; Executor compiles the whole program as one XLA
computation per feed signature.
"""
from .input_spec import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    Program, Variable, Executor, program_guard, append_backward,
    building_program, _set_building, save_inference_model,
    load_inference_model)

_static_mode = [False]
_default_main = Program()
_default_startup = Program()


def _enable():
    _static_mode[0] = True
    _set_building(_default_main)


def _disable():
    _static_mode[0] = False
    _set_building(None)


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


def data(name, shape, dtype="float32", lod_level=0):
    """Reference: static.data — declares a feed Variable in the current
    program (falls back to an InputSpec outside static mode, the
    to_static-era behavior)."""
    prog = building_program()
    if prog is None:
        return InputSpec(shape, dtype, name)
    return prog.data(name, shape, dtype)


CompiledProgram = Program  # single-device alias; DP comes from fleet


from ..amp import auto_cast as amp  # noqa: F401,E402
from . import nn  # noqa: F401,E402


# -- reference API completion (python/paddle/static/__init__.py) ----------

class BuildStrategy:
    """Reference: BuildStrategy (details/build_strategy.h) — graph-pass
    knobs. XLA owns fusion/memory passes here; accepted fields are
    recorded for introspection and otherwise advisory."""

    def __init__(self):
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True
        self.fuse_elewise_add_act_ops = True
        self.memory_optimize = True
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0


class ExecutionStrategy:
    """Reference: ExecutionStrategy — executor threading knobs (XLA/PjRt
    schedules internally; advisory)."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 100
        self.allow_op_delay = False


class ParallelExecutor:
    """Reference: ParallelExecutor (parallel_executor.cc:619). The GSPMD
    mesh replaces the SSA multi-device engine; this wrapper keeps the
    legacy construction API and executes through Executor."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 build_strategy=None, exec_strategy=None, **kwargs):
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list=None, feed=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed,
                             fetch_list=fetch_list,
                             return_numpy=return_numpy)


def cpu_places(device_count=None):
    from ..core.device import CPUPlace
    import os as _os
    n = device_count or int(_os.environ.get("CPU_NUM", 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    # API parity: on TPU builds there are no CUDA places; mirror the
    # devices we do have so place-count logic keeps working
    from ..core.device import get_place
    import jax as _jax
    ids = device_ids if device_ids is not None \
        else range(len(_jax.devices()))
    return [get_place() for _ in ids]


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """Reference: layers.create_global_var — a persistable tensor
    registered in the current program."""
    import numpy as _np
    from ..core.tensor import Tensor
    t = Tensor(_np.full(shape, value, dtype), name=name,
               persistable=True, stop_gradient=True)
    prog = building_program()
    if prog is not None:
        prog.register_persist(t)
    return t


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference: static.create_parameter."""
    import numpy as _np
    from ..core.tensor import Parameter
    from ..nn import initializer as init_mod
    init = default_initializer or (init_mod.Constant(0.0) if is_bias
                                   else init_mod.XavierNormal())
    import jax.numpy as _jnp
    val = init((tuple(shape)), dtype) if callable(init) else None
    if val is None:
        val = _np.zeros(shape, dtype)
    p = Parameter(val, name=name)
    p.stop_gradient = False
    prog = building_program()
    if prog is not None:
        prog.register_persist(p)
    return p


class _Scope:
    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        prog = building_program()
        if prog is not None and name in prog.persist:
            return prog.persist[name]
        return self.vars.get(name)


_GLOBAL_SCOPE = _Scope()


def global_scope():
    return _GLOBAL_SCOPE


import contextlib as _contextlib


@_contextlib.contextmanager
def scope_guard(scope):
    yield scope


@_contextlib.contextmanager
def device_guard(device=None):
    yield


@_contextlib.contextmanager
def name_scope(prefix=None):
    yield


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference: fluid/backward.py:1972 gradients — grad vars of
    targets wrt persistable inputs in the current static program."""
    t = targets[0] if isinstance(targets, (list, tuple)) else targets
    pg = append_backward(t, parameter_list=list(inputs)
                         if isinstance(inputs, (list, tuple)) else [inputs])
    return [g for _, g in pg]


def save(program, model_path, protocol=4, **kwargs):
    """Reference: static.save — persistables of a program."""
    import pickle
    import numpy as _np
    state = {n: _np.asarray(t._value)
             for n, t in program.persist.items()}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(state, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    """Reference: static.load — restore persistables into a program."""
    import pickle
    import jax.numpy as _jnp
    with open(model_path + ".pdparams", "rb") as f:
        state = pickle.load(f)
    for n, arr in state.items():
        if n in program.persist:
            program.persist[n]._value = _jnp.asarray(arr)


def save_program_state(program):
    import numpy as _np
    return {n: _np.asarray(t._value) for n, t in program.persist.items()}


def load_program_state(model_path, var_list=None):
    import pickle
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state):
    import jax.numpy as _jnp
    for n, arr in state.items():
        if n in program.persist:
            program.persist[n]._value = _jnp.asarray(arr)


def serialize_program(feed_vars, fetch_vars, program=None, **kwargs):
    from .program import _serialize_program
    import pickle
    prog = program or building_program()
    return pickle.dumps(_serialize_program(prog.clone(for_test=True)),
                        protocol=4)


def deserialize_program(data):
    from .program import _deserialize_program
    import pickle
    return _deserialize_program(pickle.loads(data))


def serialize_persistables(feed_vars, fetch_vars, program=None, **kwargs):
    import pickle
    import numpy as _np
    prog = program or building_program()
    return pickle.dumps({n: _np.asarray(t._value)
                         for n, t in prog.persist.items()}, protocol=4)


def deserialize_persistables(program, data, executor=None):
    import pickle
    import jax.numpy as _jnp
    for n, arr in pickle.loads(data).items():
        if n in program.persist:
            program.persist[n]._value = _jnp.asarray(arr)


def save_to_file(path, content):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path):
    with open(path, "rb") as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    return program.clone(for_test=True)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Reference: py_func_op — run arbitrary python inside the graph via
    jax.pure_callback (host callback on TPU)."""
    import jax
    import numpy as _np
    from ..core.dispatch import register_op
    from ..core.tensor import Tensor
    xs = x if isinstance(x, (list, tuple)) else [x]
    if hasattr(out, "_dtype"):          # static Variable
        out_dt = _np.dtype(out._dtype)
    elif getattr(out, "_value", None) is not None:  # Tensor
        out_dt = _np.dtype(str(out._value.dtype))
    else:
        out_dt = _np.dtype("float32")
    out_spec = jax.ShapeDtypeStruct(tuple(out.aval_shape()
                                          if hasattr(out, "aval_shape")
                                          else out.shape), out_dt)

    def _op(*arrs):
        return jax.pure_callback(
            lambda *a: _np.asarray(func(*a), out_spec.dtype), out_spec,
            *arrs)
    op = register_op(f"py_func_{id(func)}", differentiable=False)(_op)
    return op(*xs)


def accuracy(input, label, k=1, correct=None, total=None):  # noqa: A002
    """Reference: static accuracy layer."""
    from ..metric import accuracy as _acc
    return _acc(input, label, k=k)


def auc(input, label, curve="ROC", num_thresholds=200, **kwargs):  # noqa: A002
    """Reference: static auc layer (batch AUC)."""
    from ..ops import math as m, reduction as r, search as s
    import jax.numpy as _jnp
    from ..core.tensor import Tensor
    probs = input.value[:, 1] if input.aval_shape()[-1] == 2 \
        else input.value.reshape(-1)
    lab = label.value.reshape(-1)
    order = _jnp.argsort(-probs)
    lab_sorted = _jnp.take(lab, order).astype(_jnp.float32)
    tps = _jnp.cumsum(lab_sorted)
    fps = _jnp.cumsum(1.0 - lab_sorted)
    P = _jnp.maximum(tps[-1], 1e-6)
    N = _jnp.maximum(fps[-1], 1e-6)
    tpr = _jnp.concatenate([_jnp.zeros(1), tps / P])
    fpr = _jnp.concatenate([_jnp.zeros(1), fps / N])
    a = _jnp.trapezoid(tpr, fpr)
    return Tensor(a)


class Print:
    """Reference: Print op — debugging passthrough."""

    def __new__(cls, input, message=None, **kwargs):  # noqa: A002
        print(message or "", input)
        return input


class WeightNormParamAttr:
    """Reference: WeightNormParamAttr — accepted for API parity; weight
    norm itself is applied via paddle.nn.utils.weight_norm."""

    def __init__(self, dim=None, name=None, initializer=None, **kwargs):
        self.dim = dim
        self.name = name
        self.initializer = initializer


def xpu_places(device_ids=None):
    """Reference: static.xpu_places (Baidu Kunlun). No XPU in a TPU
    build; mirrors cuda_places for place-count logic."""
    return cuda_places(device_ids)
