"""paddle.static: real program-capture static graph mode.

Reference parity: python/paddle/static/ (Program/Executor/program_guard/
data/append_backward, fluid/executor.py:916, fluid/backward.py:1377).
See program.py for the TPU-native design: ops record into an editable
op-list Program; Executor compiles the whole program as one XLA
computation per feed signature.
"""
from .input_spec import InputSpec  # noqa: F401
from .program import (  # noqa: F401
    Program, Variable, Executor, program_guard, append_backward,
    building_program, _set_building, save_inference_model,
    load_inference_model)

_static_mode = [False]
_default_main = Program()
_default_startup = Program()


def _enable():
    _static_mode[0] = True
    _set_building(_default_main)


def _disable():
    _static_mode[0] = False
    _set_building(None)


def default_main_program():
    return _default_main


def default_startup_program():
    return _default_startup


def data(name, shape, dtype="float32", lod_level=0):
    """Reference: static.data — declares a feed Variable in the current
    program (falls back to an InputSpec outside static mode, the
    to_static-era behavior)."""
    prog = building_program()
    if prog is None:
        return InputSpec(shape, dtype, name)
    return prog.data(name, shape, dtype)


CompiledProgram = Program  # single-device alias; DP comes from fleet


from ..amp import auto_cast as amp  # noqa: F401,E402
from . import nn  # noqa: F401,E402
