"""Reference: tensor/to_string.py — tensor printing options
(implemented at the paddle top level, forwarded here)."""


def __getattr__(name):
    import paddle_tpu as paddle
    return getattr(paddle, name)
