"""`paddle.tensor` namespace (reference: python/paddle/tensor/ — the
tensor-function home whose names are ALSO re-exported at top level).

The implementations live in paddle_tpu/ops/; this package exposes them
under the reference submodule layout (`paddle.tensor.math.add`,
`paddle.tensor.creation.to_tensor`, ...).
"""
from ..ops import creation, linalg, logic, manipulation, math, search  # noqa: F401
from ..ops import reduction as stat  # noqa: F401  (mean/std/var/median/numel home)
from . import array, attribute, random, to_string  # noqa: F401

__all__ = []


def __getattr__(name):
    # the reference re-exports every tensor function at this level too
    # (paddle.tensor.add == paddle.add); forward instead of wildcard
    # imports, which would drag module internals (jnp, register_op...)
    # into the namespace
    import types

    import paddle_tpu as paddle

    # inplace variants live as Tensor METHODS; expose the reference's
    # free-function form paddle.tensor.add_(x, ...)
    from ..core.tensor import Tensor
    if name.endswith("_") and hasattr(Tensor, name):
        meth = getattr(Tensor, name)

        def free(x, *a, **k):
            return meth(x, *a, **k)

        free.__name__ = name
        return free
    # LoD tensor-array ops live on the fluid surface
    if name in ("create_array", "array_read", "array_write",
                "array_length"):
        from .. import fluid
        return getattr(fluid.layers, name)
    try:
        attr = getattr(paddle, name)
    except AttributeError:
        raise AttributeError(
            f"module 'paddle.tensor' has no attribute {name!r}") from None
    if isinstance(attr, types.ModuleType):
        # don't mirror sibling namespaces (paddle.tensor.nn etc. do not
        # exist in the reference surface)
        raise AttributeError(
            f"module 'paddle.tensor' has no attribute {name!r}")
    return attr
