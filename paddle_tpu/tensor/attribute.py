"""Reference: tensor/attribute.py — shape/rank/real/imag/is_complex
etc.; implemented at the paddle top level, forwarded here."""


def __getattr__(name):
    import paddle_tpu as paddle
    return getattr(paddle, name)
