"""Reference: tensor/array.py — LoD tensor-array ops (create_array /
array_read / array_write / array_length live on the fluid surface
here; this module forwards)."""


def __getattr__(name):
    from .. import fluid
    return getattr(fluid.layers, name)
