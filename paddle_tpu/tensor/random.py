"""Reference: tensor/random.py — rand/randn/randint/randperm/uniform/
normal/multinomial etc.; implemented at the paddle top level (stateless
PRNG under the hood), forwarded here."""


def __getattr__(name):
    import paddle_tpu as paddle
    return getattr(paddle, name)
