from .to_static import to_static, not_to_static, TracedFunction  # noqa: F401
from .save_load import save, load, TranslatedLayer  # noqa: F401


class ProgramTranslator:
    """Reference: dygraph_to_static/program_translator.py:232 — global
    enable/disable switch for to_static conversion."""

    _instance = None
    _enabled = [True]

    @classmethod
    def get_instance(cls):
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance

    def enable(self, enable_to_static):
        self._enabled[0] = bool(enable_to_static)

    @property
    def enable_to_static(self):
        return self._enabled[0]


def set_code_level(level=100, also_to_stdout=False):
    """Reference: jit.set_code_level — dy2static transformed-code dump
    verbosity (advisory here: trace capture has no AST dump stages)."""
    return None


def set_verbosity(level=0, also_to_stdout=False):
    return None


class TracedLayer:
    """Reference: fluid/dygraph/jit.py TracedLayer — trace a layer once,
    replay/save the captured program (here: a TracedFunction over the
    layer plus jit.save)."""

    def __init__(self, layer, traced):
        self._layer = layer
        self._traced = traced

    @staticmethod
    def trace(layer, inputs):
        traced = to_static(layer.forward)
        outs = traced(*inputs)
        return outs, TracedLayer(layer, traced)

    def __call__(self, *inputs):
        return self._traced(*inputs)

    def save_inference_model(self, path, feed=None, fetch=None, **kwargs):
        save(self._traced, path)
