from .to_static import to_static, not_to_static, TracedFunction  # noqa: F401
from .save_load import save, load, TranslatedLayer  # noqa: F401
