"""@to_static: whole-step program capture and compilation.

TPU-native replacement for the reference dygraph-to-static system
(reference: python/paddle/fluid/dygraph/dygraph_to_static/
program_translator.py:232 StaticFunction/ProgramTranslator,
partial_program.py PartialProgramLayer running a captured ProgramDesc via
the run_program op). Design difference: instead of AST-rewriting Python
control flow into program ops, we capture the actual execution trace as a
single XLA computation via jax.jit:

call 1 (per input signature): runs eagerly (warmup; lazily-created state
  like optimizer moments materializes).
call 2: runs eagerly under a recording TraceContext that discovers which
  pre-existing Tensors the function reads (compiled inputs) and mutates
  (compiled outputs written back after each call) — parameters, optimizer
  state, RNN/batch-norm stats, RNG state.
call 3+: executes the jit-compiled XLA program; mutated state buffers are
  donated, so parameter updates are in-place at the XLA level.

Python control flow is supported naturally when it doesn't depend on
traced values (it is unrolled/baked like the reference's static backend);
data-dependent branching inside a compiled step should use tensor ops
(where/cond) — same constraint the reference's static graph has.
"""
import functools

import numpy as np

import jax

from ..core import trace as trace_mod
from ..core.tensor import Tensor


def _flatten(obj, leaves):
    """Flatten nested (list/tuple/dict) structure, extracting Tensor leaves.
    Returns a structure token for cache keys."""
    if isinstance(obj, Tensor):
        leaves.append(obj)
        return ("T",)
    if isinstance(obj, (list, tuple)):
        return ("L" if isinstance(obj, list) else "t",
                tuple(_flatten(o, leaves) for o in obj))
    if isinstance(obj, dict):
        return ("D", tuple(sorted((k, _flatten(v, leaves))
                                  for k, v in obj.items())))
    return ("C", obj if _hashable_const(obj) else repr(obj))


def _hashable_const(o):
    try:
        hash(o)
        return True
    except TypeError:
        return False


def _rebuild(struct, leaf_iter):
    kind = struct[0]
    if kind == "T":
        return next(leaf_iter)
    if kind in ("L", "t"):
        seq = [_rebuild(s, leaf_iter) for s in struct[1]]
        return seq if kind == "L" else tuple(seq)
    if kind == "D":
        return {k: _rebuild(s, leaf_iter) for k, s in struct[1]}
    return struct[1]


class TracedFunction:
    def __init__(self, fn, input_spec=None, warmup=1, enable_ast=True):
        if enable_ast and not getattr(fn, "__wrapped_dy2static__", False):
            # AST-rewrite tensor-dependent if/while into lax control flow
            # (reference: dygraph_to_static program_translator.py applies
            # its AST suite under @to_static)
            from .dy2static import convert_to_static
            fn = convert_to_static(fn)
        self._fn = fn
        self._input_spec = input_spec
        # warmup=0: skip the eager pass and record on call 1 — valid when
        # all lazily-created state (optimizer moments, BN stats) already
        # exists, e.g. after one eager step at any batch size
        self._warmup = max(0, warmup)
        self._entries = {}  # signature -> dict(state)
        functools.update_wrapper(self, fn)
        self._bound_instance = None

    def __get__(self, instance, owner):
        if instance is None:
            return self
        bound = TracedFunction(self._fn.__get__(instance, owner),
                               self._input_spec, self._warmup)
        bound._entries = self._entries  # share cache across accesses
        # NOTE: methods on the same instance share compiled entries; distinct
        # instances get distinct bound closures via instance id in signature.
        bound._bound_instance = instance
        return bound

    @property
    def entries(self):
        return self._entries

    def _signature(self, args, kwargs):
        leaves = []
        struct = _flatten((args, kwargs), leaves)
        avals = tuple((tuple(t.aval_shape()), str(t.value.dtype))
                      for t in leaves)
        inst = id(self._bound_instance) if self._bound_instance is not None else 0
        return (struct, avals, inst), leaves, struct

    def __call__(self, *args, **kwargs):
        if trace_mod.current_trace() is not None:
            # nested to_static inside a trace: inline
            return self._fn(*args, **kwargs)
        sig, leaves, struct = self._signature(args, kwargs)
        entry = self._entries.get(sig)
        if entry is None:
            entry = {"calls": 0, "compiled": None, "record": None}
            self._entries[sig] = entry
        if entry["compiled"] is None:
            # Shape-polymorphic reuse: the compiled closure re-runs the
            # capture under jax.jit, which re-specializes per shape on
            # its own. A previous record with the same STRUCTURE (same
            # pytree of args, different shapes/dtypes) discovered the
            # same closure state, so new batch sizes skip the eager and
            # record passes entirely — in particular, a large-batch step
            # never executes eagerly (eager holds every intermediate
            # live and OOMs long before the compiled program would).
            donor = self._same_struct_compiled(sig, struct)
            if donor is not None:
                entry["compiled"] = donor
        if entry["compiled"] is not None:
            return self._run_compiled(entry, struct, leaves)
        entry["calls"] += 1
        if entry["calls"] <= self._warmup:
            return self._fn(*args, **kwargs)
        return self._record_and_compile(entry, args, kwargs, struct, leaves)

    def _same_struct_compiled(self, sig, struct):
        _, _, inst = sig
        for (struct2, _, inst2), e2 in self._entries.items():
            if struct2 == struct and inst2 == inst \
                    and e2.get("compiled") is not None:
                return e2["compiled"]
        return None

    # -- phase 2: record ---------------------------------------------------
    def _record_and_compile(self, entry, args, kwargs, struct, leaves):
        ctx = trace_mod.TraceContext("record")
        with trace_mod.trace_guard(ctx):
            out = self._fn(*args, **kwargs)
        if trace_mod._capture_hook is not None:
            # birth tracking on: validate the recorded graph BEFORE
            # compiling — a sub-trace value sitting in the captured
            # reads raises an attributed TracerLeakError here instead
            # of an opaque jax error at the first compiled call
            from ..analysis import birth as _birth
            _birth.check_trace(ctx)
        reads = [t for tid, t in ctx.reads.items()]
        writes = [t for tid, t in ctx.writes.items()]
        read_ids = set(ctx.reads)
        captured = reads + [t for t in writes if id(t) not in read_ids]
        mutated = writes
        mutated_in_captured = [i for i, t in enumerate(captured)
                               if id(t) in ctx.writes]
        out_leaves = []
        out_struct = _flatten(out, out_leaves)
        fn = self._fn
        grad_owners = []  # captured tensors whose .grad is created in-trace

        def compiled_fn(arg_arrays, mut_cap_arrays, ro_cap_arrays):
            jctx = trace_mod.TraceContext("jit")
            mut_caps = [captured[i] for i in mutated_in_captured]
            ro_caps = [t for i, t in enumerate(captured)
                       if i not in set(mutated_in_captured)]
            grad_owners.clear()
            with trace_mod.trace_guard(jctx):
                for t, a in zip(mut_caps, mut_cap_arrays):
                    jctx.bind(t, a)
                for t, a in zip(ro_caps, ro_cap_arrays):
                    jctx.bind(t, a)
                arg_tensors = [Tensor(a) for a in arg_arrays]
                for t in arg_tensors:
                    jctx.register_created(t)
                it = iter(arg_tensors)
                cargs, ckwargs = _rebuild(struct, it)
                result = fn(*cargs, **ckwargs)
                res_leaves = []
                _flatten(result, res_leaves)
                out_arrays = [t.value for t in res_leaves]
                mut_arrays = [jctx.final_value(t) for t in mutated]
                # Gradients created during the trace that remain attached to
                # captured tensors (the "backward inside, clear outside"
                # pattern): emit their final values so callers can read
                # .grad after a compiled step.
                grad_arrays = []
                for t in captured:
                    g = t._grad
                    if isinstance(g, Tensor) and jctx.is_created(g):
                        grad_owners.append(t)
                        grad_arrays.append(jctx.final_value(g))
            return out_arrays, mut_arrays, grad_arrays

        jitted = jax.jit(compiled_fn, donate_argnums=(1,))
        entry["compiled"] = {
            "jitted": jitted,
            "fn": compiled_fn,  # re-traceable for analysis.lint_jaxpr
            "captured": captured,
            "mutated": mutated,
            "mut_cap_idx": mutated_in_captured,
            "out_struct": out_struct,
            "grad_owners": grad_owners,
        }
        entry["record"] = None
        return out

    # -- phase 3: run compiled --------------------------------------------
    def _run_compiled(self, entry, struct, leaves):
        c = entry["compiled"]
        captured = c["captured"]
        mset = set(c["mut_cap_idx"])
        mut_caps = [captured[i].value for i in c["mut_cap_idx"]]
        ro_caps = [t.value for i, t in enumerate(captured) if i not in mset]
        arg_arrays = [t.value for t in leaves]
        try:
            out_arrays, mut_arrays, grad_arrays = c["jitted"](
                arg_arrays, mut_caps, ro_caps)
        except jax.errors.UnexpectedTracerError as e:
            # structured replacement for jax's opaque leak error: a
            # captured input carried a dead sub-trace tracer into the
            # replay. With birth tracking on the leak usually raises
            # earlier WITH provenance; this is the always-on net.
            from ..analysis.birth import TracerLeakError
            raise TracerLeakError(
                "to_static replay captured a value that escaped a "
                "cond/while sub-trace (a Tensor created inside the "
                "sub-trace was not registered with the active "
                "TraceContext — see trace_mod.adopt). Re-run under "
                "paddle_tpu.analysis.birth_tracking() to attribute "
                "the birth op/trace and escape site.\n\nOriginal "
                f"error: {e}") from e
        for t, v in zip(c["mutated"], mut_arrays):
            t._value = v
        for t, g in zip(c["grad_owners"], grad_arrays):
            t._grad = Tensor(g, stop_gradient=True)
        out_tensors = iter([Tensor(a) for a in out_arrays])
        return _rebuild(c["out_struct"], out_tensors)

    def concrete_program(self):
        return self._entries

    # -- static analysis ---------------------------------------------------
    def lint(self, passes=None, **meta):
        """Run the paddle_tpu.analysis jaxpr lint over every compiled
        entry of this traced function (the whole captured step:
        forward + backward + optimizer when they were traced).
        Abstract args are rebuilt from the entry's signature, so no
        device execution happens; the mutated-captures donation the
        compiled step uses is threaded to the ``donation`` pass.
        Returns the combined findings (see analysis.lint_jaxpr)."""
        from ..analysis import lint as lint_mod
        findings = []
        for (struct, avals, _inst), entry in self._entries.items():
            c = entry.get("compiled")
            if not c or "fn" not in c:
                continue
            arg_sds = [jax.ShapeDtypeStruct(shape, np.dtype(dtype))
                       for shape, dtype in avals]
            mset = set(c["mut_cap_idx"])
            mut_caps = [c["captured"][i].value for i in c["mut_cap_idx"]]
            ro_caps = [t.value for i, t in enumerate(c["captured"])
                       if i not in mset]
            args = (arg_sds, mut_caps, ro_caps)
            closed = jax.make_jaxpr(c["fn"])(*args)
            findings.extend(lint_mod.lint_jaxpr(
                closed, passes=passes,
                donated_invars=lint_mod.donated_invars_from_argnums(
                    args, (1,)),
                **meta))
        return findings


def to_static(function=None, input_spec=None, build_strategy=None,
              property=False, warmup=1):  # noqa: A002
    """paddle.jit.to_static equivalent."""
    def deco(fn):
        from ..nn.layer_base import Layer
        if isinstance(fn, Layer):
            layer = fn
            layer.forward = TracedFunction(layer.forward, input_spec,
                                           warmup=warmup)
            return layer
        return TracedFunction(fn, input_spec, warmup=warmup)
    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn):
    return fn
