"""Dygraph-to-static AST conversion (scoped subset).

Reference parity: python/paddle/fluid/dygraph/dygraph_to_static/
(ifelse_transformer.py, loop_transformer.py, logical_transformer.py —
the AST suite behind @to_static that rewrites Python control flow over
tensors into program ops). TPU-native design: the rewritten constructs
dispatch at RUN time — a python-bool predicate executes the plain python
branch (zero overhead, trace-unrolled like the reference's static
backend), while a Tensor predicate lowers to lax.cond / lax.while_loop
via static.nn, so data-dependent branching stays inside the compiled XLA
program instead of being silently baked to the traced branch.

Supported subset (the transformer falls back to the original function on
anything else): `if/elif/else` statements whose branches assign local
names (no early returns inside tensor-pred branches), `while` loops
mutating local names, and `and/or/not` over tensors. `for` over python
ranges/containers keeps normal python semantics (unrolled at trace time,
like the reference's static unroll of constant loops).
"""
import ast
import functools
import inspect
import textwrap
import warnings

_UNSUPPORTED = (ast.Return, ast.Break, ast.Continue, ast.Yield,
                ast.YieldFrom)


def _assigned_names(nodes):
    """Local names assigned anywhere in a list of statements."""
    names = []

    class V(ast.NodeVisitor):
        def visit_Name(self, n):
            if isinstance(n.ctx, ast.Store) and n.id not in names:
                names.append(n.id)

        def visit_AugAssign(self, n):
            if isinstance(n.target, ast.Name) and n.target.id not in names:
                names.append(n.target.id)
            self.generic_visit(n)

    for s in nodes:
        V().visit(s)
    return names


def _loaded_names(nodes):
    names = []

    class V(ast.NodeVisitor):
        def visit_Name(self, n):
            if isinstance(n.ctx, ast.Load) and n.id not in names:
                names.append(n.id)

    for s in nodes:
        V().visit(s)
    return names


def _contains_unsupported(nodes):
    for s in nodes:
        for sub in ast.walk(s):
            if isinstance(sub, _UNSUPPORTED):
                return True
    return False


class _ControlFlowTransformer(ast.NodeTransformer):
    """Rewrites if/while statements into runtime-dispatch helper calls."""

    def __init__(self):
        self.counter = 0
        self.failed = False

    def _fresh(self, base):
        self.counter += 1
        return f"__jst_{base}_{self.counter}"

    # -- if/elif/else ------------------------------------------------------
    def visit_If(self, node):
        self.generic_visit(node)
        if self.failed:
            return node
        if _contains_unsupported(node.body) or \
                _contains_unsupported(node.orelse):
            # branches with return/break/... keep python semantics; a
            # tensor predicate there raises at runtime via __bool__
            return node
        out_names = sorted(set(_assigned_names(node.body)
                               + _assigned_names(node.orelse)))
        if not out_names:
            return node
        true_name = self._fresh("true_fn")
        false_name = self._fresh("false_fn")
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in out_names],
            ctx=ast.Load()))

        def make_fn(name, body):
            # PURE branches: current values of out_names come in as
            # parameters (same names, so `y = y * 10` reads the pre-if
            # value) and updates go out via the return. No nonlocal —
            # writes must not leak between lax.cond's two branch traces.
            fargs = ast.arguments(
                posonlyargs=[],
                args=[ast.arg(arg=n) for n in out_names],
                kwonlyargs=[], kw_defaults=[], defaults=[])
            return ast.FunctionDef(
                name=name, args=fargs,
                body=(list(body) if body else [ast.Pass()]) + [ret],
                decorator_list=[], type_params=[])

        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in out_names],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__jst_convert_ifelse", ctx=ast.Load()),
                args=[node.test,
                      ast.Name(id=true_name, ctx=ast.Load()),
                      ast.Name(id=false_name, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in out_names],
                                ctx=ast.Load()),
                      ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                               args=[], keywords=[])],
                keywords=[]))
        return [make_fn(true_name, node.body),
                make_fn(false_name, node.orelse), call]

    # -- while -------------------------------------------------------------
    def visit_While(self, node):
        self.generic_visit(node)
        if self.failed or node.orelse or _contains_unsupported(node.body):
            return node
        # carry EVERY assigned name: a store-only variable's last value
        # must survive the loop too
        carried = sorted(set(_assigned_names(node.body)))
        if not carried:
            return node
        args = ast.arguments(
            posonlyargs=[],
            args=[ast.arg(arg=n) for n in carried],
            kwonlyargs=[], kw_defaults=[], defaults=[])
        ret = ast.Return(value=ast.Tuple(
            elts=[ast.Name(id=n, ctx=ast.Load()) for n in carried],
            ctx=ast.Load()))
        cond_name = self._fresh("while_cond")
        body_name = self._fresh("while_body")
        cond_fn = ast.FunctionDef(
            name=cond_name, args=args,
            body=[ast.Return(value=node.test)], decorator_list=[],
            type_params=[])
        body_fn = ast.FunctionDef(
            name=body_name, args=args,
            body=list(node.body) + [ret], decorator_list=[], type_params=[])
        call = ast.Assign(
            targets=[ast.Tuple(
                elts=[ast.Name(id=n, ctx=ast.Store()) for n in carried],
                ctx=ast.Store())],
            value=ast.Call(
                func=ast.Name(id="__jst_convert_while", ctx=ast.Load()),
                args=[ast.Name(id=cond_name, ctx=ast.Load()),
                      ast.Name(id=body_name, ctx=ast.Load()),
                      ast.Tuple(elts=[ast.Constant(value=n)
                                      for n in carried], ctx=ast.Load()),
                      ast.Call(func=ast.Name(id="locals", ctx=ast.Load()),
                               args=[], keywords=[])],
                keywords=[]))
        return [cond_fn, body_fn, call]

    # -- and/or/not over tensors ------------------------------------------
    def visit_BoolOp(self, node):
        self.generic_visit(node)
        name = ("__jst_and" if isinstance(node.op, ast.And) else "__jst_or")
        self.counter += 1
        empty_args = ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                   kw_defaults=[], defaults=[])
        out = node.values[0]
        for v in node.values[1:]:
            # rhs wrapped in a lambda: python short-circuit semantics are
            # preserved for non-tensor operands (reference:
            # logical_transformer.py does the same)
            out = ast.Call(func=ast.Name(id=name, ctx=ast.Load()),
                           args=[out, ast.Lambda(args=empty_args, body=v)],
                           keywords=[])
        return out

    def visit_UnaryOp(self, node):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            self.counter += 1
            return ast.Call(func=ast.Name(id="__jst_not", ctx=ast.Load()),
                            args=[node.operand], keywords=[])
        return node


# -- runtime helpers --------------------------------------------------------

def _is_tensor(x):
    from ..core.tensor import Tensor
    return isinstance(x, Tensor)


class _Undefined:
    """Placeholder for an out_name not yet bound before the if statement.
    Any USE raises, matching python's UnboundLocalError for a variable the
    taken branch never assigned; assign-then-use inside a branch is fine
    (the parameter is simply overwritten)."""

    def _raise(self, *a, **k):
        raise UnboundLocalError(
            "local variable referenced before assignment (a to_static "
            "converted branch left it undefined)")

    __bool__ = __iter__ = __call__ = __len__ = _raise
    __add__ = __radd__ = __sub__ = __rsub__ = __mul__ = __rmul__ = _raise
    __truediv__ = __rtruediv__ = __getitem__ = __eq__ = __lt__ = _raise

    def __getattr__(self, name):
        self._raise()

    def __repr__(self):
        return "<undefined local>"


_UNDEF = _Undefined()


def convert_ifelse(pred, true_fn, false_fn, out_names, local_ns):
    """Runtime dispatch (reference: dygraph_to_static convert_ifelse).
    Python predicate -> plain python branch. Tensor predicate -> lax.cond
    via static.nn; both branches are pure functions of the current
    out_name values and must produce every output."""
    args = [local_ns.get(n, _UNDEF) for n in out_names]
    if not _is_tensor(pred):
        return true_fn(*args) if pred else false_fn(*args)
    from ..static import nn as snn
    try:
        outs = snn.cond(pred, lambda: true_fn(*args),
                        lambda: false_fn(*args))
    except TypeError as e:
        # an <undefined local> placeholder reached jnp.asarray: a branch
        # read or returned an out_name it never assigned
        if "_Undefined" in str(e) or "undefined local" in str(e):
            raise RuntimeError(
                "to_static if/else on a Tensor predicate: every converted "
                f"output {list(out_names)} must be assigned in BOTH "
                "branches or defined before the if statement") from e
        raise
    # call site always tuple-unpacks the out_names
    return outs if isinstance(outs, tuple) else (outs,)


def convert_while(cond_fn, body_fn, out_names, local_ns):
    """Runtime dispatch for while loops: python condition -> plain loop;
    Tensor condition -> lax.while_loop via static.nn."""
    carried = tuple(local_ns.get(n, _UNDEF) for n in out_names)
    first = cond_fn(*carried)
    if not _is_tensor(first):
        vals = carried
        while cond_fn(*vals):
            out = body_fn(*vals)
            vals = out if isinstance(out, tuple) else (out,)
        return vals
    from ..static import nn as snn
    out = snn.while_loop(cond_fn, lambda *a: body_fn(*a), list(carried))
    return tuple(out)


def convert_logical_and(a, b_fn):
    """b_fn is lazy: python short-circuit is preserved for non-tensors."""
    if _is_tensor(a):
        from ..ops import logic
        b = b_fn()
        return logic.logical_and(a, b)
    if not a:
        return a
    b = b_fn()
    if _is_tensor(b):
        from ..ops import logic
        return logic.logical_and(a, b)
    return b


def convert_logical_or(a, b_fn):
    if _is_tensor(a):
        from ..ops import logic
        return logic.logical_or(a, b_fn())
    if a:
        return a
    b = b_fn()
    if _is_tensor(b):
        from ..ops import logic
        return logic.logical_or(a, b)
    return b


def convert_logical_not(a):
    if _is_tensor(a):
        from ..ops import logic
        return logic.logical_not(a)
    return not a


class _GlobalsProxy(dict):
    """exec globals that fall back to the original module globals — late-
    bound helpers and recursion resolve at call time like undecorated
    python."""

    def __init__(self, base, extra):
        super().__init__(extra)
        self._base = base

    def __missing__(self, key):
        return self._base[key]


def convert_to_static(fn):
    """AST-rewrite fn's control flow; returns the original fn when the
    source is unavailable or the rewrite does not apply."""
    import types
    if inspect.ismethod(fn):
        inner = convert_to_static(fn.__func__)
        if inner is fn.__func__:
            return fn
        return types.MethodType(inner, fn.__self__)
    if getattr(fn, "__wrapped__", None) is not None \
            and getattr(fn, "__code__", None) is not \
            getattr(inspect.unwrap(fn), "__code__", None):
        # fn is a decorator wrapper around the real function —
        # inspect.getsource would return the INNER source and re-execing
        # it would silently drop the wrapper; keep trace semantics instead
        warnings.warn(
            f"dy2static: {fn.__name__} is wrapped by another decorator; "
            "skipping AST conversion (tensor-dependent python control "
            "flow will be baked at trace time)")
        return fn
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
        fdef = tree.body[0]
        # drop decorators so exec doesn't re-wrap
        fdef.decorator_list = []
        tr = _ControlFlowTransformer()
        new_tree = tr.visit(tree)
        if tr.failed or tr.counter == 0:
            return fn  # nothing rewritten
        ast.fix_missing_locations(new_tree)
        code = compile(new_tree, filename=f"<dy2static {fn.__name__}>",
                       mode="exec")
        extra = {"__jst_convert_ifelse": convert_ifelse,
                 "__jst_convert_while": convert_while,
                 "__jst_and": convert_logical_and,
                 "__jst_or": convert_logical_or,
                 "__jst_not": convert_logical_not}
        # closures: materialize free variables as globals of the new fn
        if fn.__closure__:
            for name, cell in zip(fn.__code__.co_freevars, fn.__closure__):
                try:
                    extra[name] = cell.cell_contents
                except ValueError:
                    pass
        globs = _GlobalsProxy(fn.__globals__, extra)
        ns = {}
        exec(code, globs, ns)
        new_fn = ns[fn.__name__]
        functools.update_wrapper(new_fn, fn)
        new_fn.__wrapped_dy2static__ = True
        return new_fn
    except (OSError, TypeError, SyntaxError) as e:
        warnings.warn(f"dy2static: could not convert {fn!r} ({e}); "
                      "tensor-dependent python control flow will be baked "
                      "at trace time")
        return fn
