"""jit.save / jit.load: inference model export.

Reference parity: python/paddle/fluid/dygraph/jit.py:515 (jit.save exports
ProgramDesc+params) / :876 (jit.load -> TranslatedLayer). TPU-native
format: the forward computation is serialized with jax.export (portable
StableHLO), parameters with paddle.save. A loaded TranslatedLayer executes
the deserialized XLA program directly — the analogue of AnalysisPredictor
running a saved inference program (reference:
paddle/fluid/inference/api/analysis_predictor.h:82).
"""
import os
import pickle

import numpy as np
import jax
import jax.export  # binds the jax.export attribute on older releases
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import trace as trace_mod
from .to_static import TracedFunction


def save(layer, path, input_spec=None, **configs):
    """Export layer.forward as StableHLO + params. input_spec: list of
    example Tensors or InputSpec-like objects with .shape/.dtype."""
    from ..static.input_spec import InputSpec
    from ..framework.io_utils import save as psave
    if input_spec is None:
        raise ValueError("jit.save requires input_spec (example inputs or "
                         "InputSpec list) in paddle_tpu")
    from ..core.dtype import to_jax_dtype
    examples = []       # concrete fallback args
    poly_examples = []  # symbolic-dim args (dynamic batch etc.)
    n_sym = 0
    for spec in input_spec:
        if isinstance(spec, Tensor):
            examples.append(spec.value)
            poly_examples.append(spec.value)
        elif isinstance(spec, InputSpec):
            dtype = to_jax_dtype(spec.dtype)
            shape = tuple(1 if (s is None or s < 0) else int(s)
                          for s in spec.shape)
            examples.append(jnp.zeros(shape, dtype))
            if any(s is None or s < 0 for s in spec.shape):
                # dynamic dims -> jax.export symbolic dimensions, so the
                # loaded program accepts any size (reference ProgramDesc
                # keeps -1 dims; StableHLO equivalent is shape polymorphism)
                dims = []
                for s in spec.shape:
                    if s is None or s < 0:
                        dims.append(f"_d{n_sym}")
                        n_sym += 1
                    else:
                        dims.append(str(int(s)))
                sym = jax.export.symbolic_shape(",".join(dims))
                poly_examples.append(jax.ShapeDtypeStruct(sym, dtype))
            else:
                poly_examples.append(jnp.zeros(shape, dtype))
        else:
            examples.append(jnp.asarray(spec))
            poly_examples.append(jnp.asarray(spec))

    fwd = layer.forward
    if isinstance(fwd, TracedFunction):
        fwd = fwd._fn

    layer.eval()
    params = layer.state_dict()
    names = list(params.keys())
    values = [params[n].value for n in names]

    def pure_fn(param_values, *inputs):
        # run the layer with parameters substituted functionally
        ctx = trace_mod.TraceContext("jit")
        with trace_mod.trace_guard(ctx):
            for n, v in zip(names, param_values):
                ctx.bind(params[n], v)
            in_tensors = [Tensor(x) for x in inputs]
            for t in in_tensors:
                ctx.register_created(t)
            out = layer(*in_tensors)
            outs = out if isinstance(out, (list, tuple)) else [out]
            return [o.value for o in outs]

    jitted = jax.jit(pure_fn)
    if n_sym:
        try:
            exported = jax.export.export(jitted)(values, *poly_examples)
        except Exception:
            # shape-polymorphic tracing can fail on programs with
            # size-dependent constants (reshape to literal sizes, etc.);
            # fall back to the concrete example shapes
            import warnings
            warnings.warn("jit.save: dynamic-dim export failed; saving "
                          "with concrete example shapes instead")
            exported = jax.export.export(jitted)(values, *examples)
    else:
        exported = jax.export.export(jitted)(values, *examples)
    blob = exported.serialize()

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        f.write(blob)
    psave(params, path + ".pdiparams")
    meta = {"num_inputs": len(examples), "param_names": names}
    with open(path + ".pdmeta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer:
    """Loaded inference model (reference: jit.py:876 TranslatedLayer)."""

    def __init__(self, exported, params, names):
        self._exported = exported
        self._param_values = [params[n].value if isinstance(params[n], Tensor)
                              else jnp.asarray(params[n]) for n in names]
        self._params = params

    def __call__(self, *inputs):
        arrays = [x.value if isinstance(x, Tensor) else jnp.asarray(x)
                  for x in inputs]
        outs = self._exported.call(self._param_values, *arrays)
        outs = [Tensor(o) for o in outs]
        return outs[0] if len(outs) == 1 else tuple(outs)

    forward = __call__

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")

    def state_dict(self):
        return self._params


def load(path, **configs):
    from ..framework.io_utils import load as pload
    with open(path + ".pdmodel", "rb") as f:
        blob = f.read()
    exported = jax.export.deserialize(blob)
    params = pload(path + ".pdiparams")
    with open(path + ".pdmeta", "rb") as f:
        meta = pickle.load(f)
    params = {k: Tensor(v) if isinstance(v, (np.ndarray, jnp.ndarray)) else v
              for k, v in params.items()}
    return TranslatedLayer(exported, params, meta["param_names"])
