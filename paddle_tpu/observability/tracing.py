"""Bounded host-span ring buffer + chrome://tracing export.

The device timeline already exists (profiler.Profiler's XPlane capture,
the reference DeviceTracer analogue) — but it needs a live
jax.profiler session and TensorBoard/XProf to read. This recorder is
the HOST half: every ``profiler.record_scope`` feeds it (alongside the
XPlane annotation and the metrics-registry accrual — one scope, three
sinks), so the serving engine's step anatomy (admission → grouped
prefill → decode dispatch → harvest → retirement) and the training
loop's step/optimizer scopes are inspectable after the fact with zero
capture setup: ``dump_chrome_trace()`` writes a JSON Trace Event file
that chrome://tracing and https://ui.perfetto.dev open directly
(reference parity: tools/timeline.py building a chrome trace from the
profiler proto).

The buffer is a fixed-capacity ring (collections.deque maxlen):
sustained traffic overwrites the oldest spans instead of growing —
recording is always-on and O(1) per span with a single lock.
"""
import collections
import json
import os
import threading
import time


class HostSpan:
    """One completed host scope: [t0, t0+dur) seconds on thread tid."""

    __slots__ = ("name", "t0", "dur", "tid", "args")

    def __init__(self, name, t0, dur, tid, args=None):
        self.name = name
        self.t0 = float(t0)
        self.dur = float(dur)
        self.tid = int(tid)
        self.args = args

    @property
    def t1(self):
        return self.t0 + self.dur


class HostSpanRecorder:
    """Thread-safe bounded recorder of completed host spans.

    Spans arrive at scope EXIT (record_scope knows its duration only
    then), so within one thread children are recorded before their
    parent — the chrome export doesn't care: complete ("X") events
    carry absolute ts+dur and nest by containment in the viewer.
    """

    def __init__(self, capacity=65536):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._buf = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._pid = os.getpid()

    def record(self, name, t0, dur, args=None):
        span = HostSpan(name, t0, dur, threading.get_ident(), args)
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(span)
        return span

    def __len__(self):
        with self._lock:
            return len(self._buf)

    @property
    def dropped(self):
        """Spans overwritten by the ring since the last clear()."""
        return self._dropped

    def spans(self):
        with self._lock:
            return list(self._buf)

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._dropped = 0

    # ---------------------------------------------------------- export
    def chrome_trace(self, process_name="paddle_tpu"):
        """The trace as a dict in Chrome Trace Event JSON format:
        complete ("X") events in microseconds with stable pid/tid,
        plus process/thread-name metadata events. Load with
        chrome://tracing or ui.perfetto.dev."""
        spans = self.spans()
        pid = self._pid
        events = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }]
        for tid in sorted({s.tid for s in spans}):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": f"host-{tid}"},
            })
        for s in spans:
            ev = {
                "name": s.name, "ph": "X", "cat": "host",
                "ts": round(s.t0 * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "pid": pid, "tid": s.tid,
            }
            if s.args:
                ev["args"] = dict(s.args)
            events.append(ev)
        # deterministic viewer order: by start time, metadata first
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"recorder": "paddle_tpu.observability",
                              "dropped_spans": self._dropped}}

    def dump_chrome_trace(self, path, process_name="paddle_tpu"):
        """Write the chrome trace JSON to ``path``; returns the path."""
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(process_name), fh)
        return path


_default_recorder = HostSpanRecorder()


def default_recorder():
    """The process-global recorder profiler.record_scope feeds."""
    return _default_recorder


class span_timer:
    """Context manager recording one span into a recorder — the
    non-profiler entry point (record_scope is the instrumented path;
    this is for host-only phases that must not touch jax)."""

    def __init__(self, name, recorder=None, args=None):
        self.name = name
        self.recorder = recorder or _default_recorder
        self.args = args
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.recorder.record(self.name, self._t0,
                             time.perf_counter() - self._t0, self.args)
        return False
