"""Bounded host-span ring buffer + chrome://tracing export.

The device timeline already exists (profiler.Profiler's XPlane capture,
the reference DeviceTracer analogue) — but it needs a live
jax.profiler session and TensorBoard/XProf to read. This recorder is
the HOST half: every ``profiler.record_scope`` feeds it (alongside the
XPlane annotation and the metrics-registry accrual — one scope, three
sinks), so the serving engine's step anatomy (admission → grouped
prefill → decode dispatch → harvest → retirement) and the training
loop's step/optimizer scopes are inspectable after the fact with zero
capture setup: ``dump_chrome_trace()`` writes a JSON Trace Event file
that chrome://tracing and https://ui.perfetto.dev open directly
(reference parity: tools/timeline.py building a chrome trace from the
profiler proto).

The buffer is a fixed-capacity ring (collections.deque maxlen):
sustained traffic overwrites the oldest spans instead of growing —
recording is always-on and O(1) per span with a single lock.

Besides complete ("X") spans the recorder holds chrome FLOW events
(``ph:"s"/"t"/"f"``): the request flight recorder
(observability.flight) emits one flow chain per request, so Perfetto
draws arrows linking a request's enqueue → admit → prefill → first
token → retire markers ACROSS the engine step spans — the Dapper-style
"follow one request" view. Flow events bind to the slice enclosing
their timestamp on the same pid/tid, so every flow emission pairs with
a marker span at the identical timestamp.
"""
import collections
import json
import os
import threading
import time


class HostSpan:
    """One completed host scope: [t0, t0+dur) seconds on thread tid."""

    __slots__ = ("name", "t0", "dur", "tid", "args")

    def __init__(self, name, t0, dur, tid, args=None):
        self.name = name
        self.t0 = float(t0)
        self.dur = float(dur)
        self.tid = int(tid)
        self.args = args

    @property
    def t1(self):
        return self.t0 + self.dur


class FlowEvent:
    """One chrome flow-event point: phase "s" (start), "t" (step) or
    "f" (finish) of flow chain ``fid`` at instant ``t`` on thread
    ``tid``. Chains with the same (cat, id) render as arrows between
    the slices enclosing each point."""

    __slots__ = ("name", "t", "phase", "fid", "tid", "args")

    def __init__(self, name, t, phase, fid, tid, args=None):
        if phase not in ("s", "t", "f"):
            raise ValueError(f"flow phase must be 's', 't' or 'f', "
                             f"got {phase!r}")
        self.name = name
        self.t = float(t)
        self.phase = phase
        self.fid = int(fid)
        self.tid = int(tid)
        self.args = args


class HostSpanRecorder:
    """Thread-safe bounded recorder of completed host spans.

    Spans arrive at scope EXIT (record_scope knows its duration only
    then), so within one thread children are recorded before their
    parent — the chrome export doesn't care: complete ("X") events
    carry absolute ts+dur and nest by containment in the viewer.
    """

    def __init__(self, capacity=65536):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._buf = collections.deque(maxlen=self.capacity)
        self._flows = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._dropped = 0
        self._flows_dropped = 0
        self._pid = os.getpid()

    def record(self, name, t0, dur, args=None):
        span = HostSpan(name, t0, dur, threading.get_ident(), args)
        with self._lock:
            if len(self._buf) == self.capacity:
                self._dropped += 1
            self._buf.append(span)
        return span

    def record_flow(self, name, t, phase, flow_id, args=None):
        """Record one flow-event point ("s"/"t"/"f") of chain
        ``flow_id`` at instant ``t`` on the calling thread. Pair it
        with a marker span at the same timestamp so viewers have a
        slice to bind the arrow to."""
        ev = FlowEvent(name, t, phase, flow_id, threading.get_ident(),
                       args)
        with self._lock:
            if len(self._flows) == self._flows.maxlen:
                self._flows_dropped += 1
            self._flows.append(ev)
        return ev

    def __len__(self):
        with self._lock:
            return len(self._buf)

    @property
    def dropped(self):
        """Spans overwritten by the ring since the last clear()."""
        return self._dropped

    def spans(self):
        with self._lock:
            return list(self._buf)

    def flows(self):
        with self._lock:
            return list(self._flows)

    def clear(self):
        with self._lock:
            self._buf.clear()
            self._flows.clear()
            self._dropped = 0
            self._flows_dropped = 0

    # ---------------------------------------------------------- export
    def chrome_trace(self, process_name="paddle_tpu"):
        """The trace as a dict in Chrome Trace Event JSON format:
        complete ("X") events in microseconds with stable pid/tid,
        flow events ("s"/"t"/"f") linking request lifecycles across
        spans, plus process/thread-name metadata events. Load with
        chrome://tracing or ui.perfetto.dev."""
        spans = self.spans()
        flows = self.flows()
        pid = self._pid
        events = [{
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": process_name},
        }]
        for tid in sorted({s.tid for s in spans}
                          | {f.tid for f in flows}):
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid,
                "tid": tid, "args": {"name": f"host-{tid}"},
            })
        for s in spans:
            ev = {
                "name": s.name, "ph": "X", "cat": "host",
                "ts": round(s.t0 * 1e6, 3),
                "dur": round(s.dur * 1e6, 3),
                "pid": pid, "tid": s.tid,
            }
            if s.args:
                ev["args"] = dict(s.args)
            events.append(ev)
        for f in flows:
            ev = {
                "name": f.name, "ph": f.phase, "cat": "request",
                "id": f.fid, "ts": round(f.t * 1e6, 3),
                "pid": pid, "tid": f.tid,
            }
            if f.phase == "f":
                ev["bp"] = "e"  # bind the finish to the ENCLOSING slice
            if f.args:
                ev["args"] = dict(f.args)
            events.append(ev)
        # deterministic viewer order: by start time, metadata first;
        # stable sort keeps a flow point after the span it binds to
        # when both share a timestamp
        events.sort(key=lambda e: (e["ph"] != "M", e.get("ts", 0.0)))
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"recorder": "paddle_tpu.observability",
                              "dropped_spans": self._dropped,
                              "dropped_flows": self._flows_dropped}}

    def dump_chrome_trace(self, path, process_name="paddle_tpu"):
        """Write the chrome trace JSON to ``path``; returns the path."""
        with open(path, "w") as fh:
            json.dump(self.chrome_trace(process_name), fh)
        return path


_default_recorder = HostSpanRecorder()


def default_recorder():
    """The process-global recorder profiler.record_scope feeds."""
    return _default_recorder


class span_timer:
    """Context manager recording one span into a recorder — the
    non-profiler entry point (record_scope is the instrumented path;
    this is for host-only phases that must not touch jax)."""

    def __init__(self, name, recorder=None, args=None):
        self.name = name
        self.recorder = recorder or _default_recorder
        self.args = args
        self._t0 = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.recorder.record(self.name, self._t0,
                             time.perf_counter() - self._t0, self.args)
        return False
