"""Unified observability layer: metrics registry, host-span chrome
tracing, compile watchdog.

The reference stack treats observability as a platform subsystem
(profiler.h RecordEvent/EnableProfiler, the CUPTI DeviceTracer
timeline, tools/timeline.py). This package is its operational,
TPU-native generalization, built around ONE instrumentation point:
``paddle_tpu.profiler.record_scope(name)`` feeds three sinks at once —

  1. the **XLA trace**  (TraceAnnotation + named_scope: op metadata in
     a live XPlane capture, as before);
  2. the **host timeline** (tracing.HostSpanRecorder: a bounded ring
     buffer dumpable as chrome://tracing / Perfetto JSON, no capture
     session needed);
  3. the **dashboard** (registry.default_registry(): per-scope
     seconds + call counters, scrapeable as Prometheus text).

The serving engine and the hapi training loop both instrument through
it, so `serving/*`, `hapi/*` and `optimizer/*` scopes land in all
three views. The third pillar, watchdog.CompileWatchdog, turns the
serving engine's exact compile counter into an ATTRIBUTED invariant:
every compile logs its key + abstract-shape signature + call-site,
and any compile after ``declare_warmup_complete()`` is flagged (or
raised) with that attribution.

Quick start::

    from paddle_tpu import observability as obs

    reg = obs.MetricsRegistry()
    reqs = reg.counter("requests_total", "requests served")
    reqs.inc()
    print(reg.prometheus_text())          # scrape format
    server = obs.start_metrics_server(reg)  # GET /metrics, /metrics.json

    obs.default_recorder().dump_chrome_trace("host_trace.json")
    # -> open in chrome://tracing or ui.perfetto.dev

PR 4 adds the REQUEST-level layer on top: flight.FlightRecorder gives
every serving request a lifecycle trace (enqueued -> admitted ->
prefill -> first token -> retired) flow-linked across engine step
spans in the chrome trace; slo.SLOTracker accounts SLO attainment,
goodput tokens, and sliding-window (registry.WindowedReservoir)
p50/p90/p99; watchdog compile records carry device cost telemetry
(executable_cost / device_memory_stats — graceful None on backends
that don't report). start_metrics_server() now returns a cleanly
stoppable MetricsServerHandle and mounts engine debug endpoints
(/debug/requests, /debug/state) via extra_routes.

PR 8 closes the loop with the health observatory (health/): a per-step
ledger of structured engine-state rows, pluggable online anomaly
detectors (step-time spike, queue stall, goodput collapse, KV-block
leak, steady-state compile) counted in
``serving_anomalies_total{detector}``, and debounced black-box
incident bundles on disk — rolled up at ``/debug/health`` (the
per-replica router signal) and ``/debug/ledger``.

PR 10 adds the performance observatory (perf/): per-program
device-time attribution (every AOT dispatch's measured dispatch/sync
wall accumulated per program key — ``snapshot()["perf"]``,
``/debug/perf``), a decode-step roofline model joined with
``executable_cost`` into ``serving_roofline_fraction{program}``, and
the cross-run perf ledger + ``tools/perf_diff.py`` regression gate.

PR 13 adds the cache observatory (cache/): SHARDS-style sampled
reuse-distance / miss-ratio-curve estimation over the paged KV block
economy ("what would hit-rate be at 2x capacity" — the ROADMAP-#5
spill-tier sizing tool), the top-K hot-prefix heat digest (the
ROADMAP-#2 router affinity signal), per-request cache-savings
attribution (cached tokens x measured per-token prefill cost ->
estimated TTFT ms saved), and eviction-churn telemetry (block
lifetimes + the radix thrash counter feeding the ``cache_thrash``
detector) — rolled up at ``snapshot()["cache"]`` / ``/debug/cache``
and merged exactly into the fleet view.

PR 11 adds the fleet observatory (fleet/): replica identity
(``replica_id`` / ``serving_uptime_seconds`` /
``paddle_tpu_build_info`` on every engine), a resilient
multi-replica scrape poller (per-replica timeout, backoff, staleness,
eviction/readmission ``up|stale|down`` verdicts), federated rollups
whose counters sum and fixed-bucket histograms merge bucket-wise
(fleet percentiles from merged buckets, never averaged percentiles),
``scope="fleet"`` detectors (replica_flap / fleet_goodput_collapse /
load_skew), and a FleetServer exposing ``/fleet/health`` /
``/fleet/state`` / ``/fleet/metrics`` — the surface the ROADMAP
direction-#2 router consumes.
"""
from .cache import (  # noqa: F401
    CACHE_KEYS, CacheObservatory, ReuseDistanceSampler,
    disabled_cache_report, exact_mrc, merge_heat_digests,
    merge_mrc_points, top_prefix_digest,
)
from .fleet import (  # noqa: F401
    FleetPoller, FleetServer, ReplicaIdentity, default_replica_id,
)
from .flight import (  # noqa: F401
    FlightRecorder, RequestTrace,
)
from .health import (  # noqa: F401
    HealthMonitor, IncidentRecorder, LEDGER_ROW_KEYS, StepLedger,
    build_detectors, detector_names, disabled_health_summary,
    register_detector, unregister_detector,
)
from .perf import (  # noqa: F401
    PERF_KEYS, PERF_PROGRAM_KEYS, PERF_SPEC_KEYS, ProgramPerf,
    disabled_perf_report, disabled_spec_report, format_program_key,
    hbm_bps_for,
)
from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry, MetricsServerHandle,
    Reservoir, WindowedReservoir, DEFAULT_TIME_BUCKETS,
    default_registry, merge_histogram_snapshots,
    percentile_from_buckets, prometheus_text_from_snapshots,
    start_metrics_server,
)
from .slo import SLOTracker  # noqa: F401
from .tenant import (  # noqa: F401
    TENANT_ENTRY_KEYS, TENANT_KEYS, TenantLedger,
    disabled_tenant_report,
)
from .tracing import (  # noqa: F401
    FlowEvent, HostSpan, HostSpanRecorder, default_recorder, span_timer,
)
from .watchdog import (  # noqa: F401
    CompileAfterWarmupError, CompileWatchdog, abstract_signature,
    device_memory_stats, executable_cost, watch_jax_lowering,
)
