"""Thread-safe metrics registry: counters, gauges, fixed-bucket
histograms — with labels, a stable JSON snapshot, and Prometheus text
exposition.

Reference parity: the platform layer's profiler counters
(paddle/fluid/platform/profiler.h EnableProfiler aggregates event
totals) generalized into the operational form a serving fleet actually
scrapes. Pure stdlib: no prometheus_client dependency — the text
format (HELP/TYPE lines, label escaping, cumulative ``le`` histogram
buckets) is emitted directly and pinned by tests/test_observability.py.

Three metric kinds, one family model:

  * ``Counter``  — monotone float total, ``inc(n)``;
  * ``Gauge``    — settable float, ``set(v)`` / ``inc`` / ``dec``;
  * ``Histogram``— fixed upper-bound buckets declared at registration
                   (never resized: bounded memory under sustained
                   traffic — the reason ServingMetrics' unbounded
                   latency lists moved here), ``observe(v)`` with
                   cumulative bucket counts + sum + count exposition.

A family declared with ``labelnames`` hands out per-label-value
children via ``labels(...)``; without labelnames the family IS its
single child (``counter.inc()`` just works). ``MetricsRegistry`` is
fully lock-protected; one global ``default_registry()`` backs the
framework-wide span accounting (profiler.record_scope's third sink).

``start_metrics_server(registry)`` serves ``/metrics`` (Prometheus
text) and ``/metrics.json`` (the snapshot) from a stdlib
ThreadingHTTPServer daemon thread — the serving engine exposes it as
``ServingEngine.serve_metrics()``.
"""
import collections
import json
import math
import random
import threading
import time

# prometheus-style latency buckets (seconds): sub-ms to tens of seconds
DEFAULT_TIME_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0)

_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name):
    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise ValueError(f"invalid metric name {name!r}")
    return name


def _escape_label(value):
    """Prometheus label-value escaping: backslash, double-quote, LF."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(text):
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v):
    """Sample-value formatting: integers without a trailing .0;
    non-finite values in canonical Prometheus spelling."""
    f = float(v)
    if not math.isfinite(f):
        return "NaN" if f != f else ("+Inf" if f > 0 else "-Inf")
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class _Child:
    """One (labelvalues) series of a counter/gauge family."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock):
        self._lock = lock
        self._value = 0.0

    @property
    def value(self):
        return self._value

    def inc(self, amount=1.0):
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += amount

    def set_to(self, value):
        """Absolute set — the facade hook for code that keeps a python
        attribute in sync (ServingMetrics' ``metrics.compiles += 1``
        property pattern)."""
        with self._lock:
            self._value = float(value)


class _GaugeChild(_Child):
    __slots__ = ("_fn", "_on_error")

    def __init__(self, lock, on_error=None):
        super().__init__(lock)
        self._fn = None
        self._on_error = on_error

    @property
    def value(self):
        fn = self._fn
        if fn is not None:
            # called OUTSIDE the registry lock: the callback may take
            # its own locks (reservoir pruning); a failing callback
            # must not 500 the scrape — the series exports NaN and the
            # failure is counted in metrics_scrape_errors_total
            try:
                return float(fn())
            except Exception:
                if self._on_error is not None:
                    try:
                        self._on_error()
                    except Exception:
                        pass
                return float("nan")
        return self._value

    def inc(self, amount=1.0):
        with self._lock:
            self._value += amount

    def dec(self, amount=1.0):
        self.inc(-amount)

    def set(self, value):
        with self._lock:
            self._value = float(value)

    def set_function(self, fn):
        """Make this gauge PULL its value from ``fn()`` at every
        exposition (snapshot / Prometheus scrape) — the sliding-window
        percentile gauges use this so /metrics reflects the window at
        scrape time, not at the last observation."""
        with self._lock:
            self._fn = fn


class _HistogramChild:
    """Fixed-bucket histogram series: bucket counts stay per-bucket
    internally and cumulate only at exposition/snapshot time."""

    __slots__ = ("_lock", "_bounds", "_counts", "_sum", "_count")

    def __init__(self, lock, bounds):
        self._lock = lock
        self._bounds = bounds
        self._counts = [0] * (len(bounds) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._count = 0

    def observe(self, value):
        v = float(value)
        with self._lock:
            i = 0
            for i, b in enumerate(self._bounds):
                if v <= b:
                    break
            else:
                i = len(self._bounds)
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    @property
    def sum(self):
        return self._sum

    @property
    def count(self):
        return self._count

    def cumulative_buckets(self):
        """[(upper_bound_label, cumulative_count), ...] ending at +Inf."""
        out, acc = [], 0
        with self._lock:
            counts = list(self._counts)
        for b, c in zip(self._bounds, counts):
            acc += c
            out.append((format(b, "g"), acc))
        out.append(("+Inf", acc + counts[-1]))
        return out


# where an over-cardinality label value folds to: one shared overflow
# series per family instead of an unbounded child dict (an adversarial
# label flood — 10k unique tenant ids, say — must cost O(cap) memory)
OVERFLOW_LABEL = "~other"


class _Family:
    """A named metric family: help text, label names, children.

    Label cardinality is BOUNDED: once a family holds
    ``registry.max_label_values`` distinct label-value tuples, any NEW
    tuple folds into the ``OVERFLOW_LABEL`` series (every label
    position set to ``~other``) and the fold is counted in the
    lazily-registered ``metrics_label_overflow_total{family}`` counter
    — so a label flood degrades to one aggregate series plus an
    attributed alarm, never an unbounded registry."""

    kind = None

    def __init__(self, registry, name, help_text, labelnames):
        self.name = _check_name(name)
        self.help = help_text
        self.labelnames = tuple(labelnames)
        for ln in self.labelnames:
            _check_name(ln)
        self._registry = registry
        self._lock = registry._lock
        self._children = {}
        if not self.labelnames:
            self._children[()] = self._make_child()

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kwvalues):
        if kwvalues:
            if values:
                raise ValueError("pass label values positionally OR by "
                                 "name, not both")
            values = tuple(kwvalues[ln] for ln in self.labelnames)
        values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} expects labels {self.labelnames}, got "
                f"{values}")
        folded = False
        with self._lock:
            child = self._children.get(values)
            if child is None:
                cap = getattr(self._registry, "max_label_values", 0)
                if cap and len(self._children) >= cap:
                    folded = True
                    values = tuple(OVERFLOW_LABEL for _ in values)
                    child = self._children.get(values)
                if child is None:
                    child = self._children[values] = self._make_child()
        if folded and self.name != "metrics_label_overflow_total":
            self._registry.label_overflow(self.name)
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; call "
                f".labels(...) first")
        return self._children[()]

    def series(self):
        """Stable-ordered [(labelvalues, child)] view."""
        with self._lock:
            return sorted(self._children.items())


class Counter(_Family):
    kind = "counter"

    def _make_child(self):
        return _Child(self._lock)

    def inc(self, amount=1.0):
        self._default().inc(amount)

    def set_to(self, value):
        self._default().set_to(value)

    @property
    def value(self):
        return self._default().value


class Gauge(_Family):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock, on_error=self._scrape_error)

    def _scrape_error(self):
        self._registry.scrape_error(self.name)

    def set(self, value):
        self._default().set(value)

    def inc(self, amount=1.0):
        self._default().inc(amount)

    def dec(self, amount=1.0):
        self._default().dec(amount)

    def set_function(self, fn):
        self._default().set_function(fn)

    @property
    def value(self):
        return self._default().value


class Histogram(_Family):
    kind = "histogram"

    def __init__(self, registry, name, help_text, labelnames, buckets):
        bounds = tuple(sorted(float(b) for b in buckets))
        if not bounds:
            raise ValueError("histogram needs at least one bucket")
        self.buckets = bounds
        super().__init__(registry, name, help_text, labelnames)

    def _make_child(self):
        return _HistogramChild(self._lock, self.buckets)

    def observe(self, value):
        self._default().observe(value)

    @property
    def sum(self):
        return self._default().sum

    @property
    def count(self):
        return self._default().count


class Reservoir:
    """Fixed-size uniform sample of an unbounded observation stream
    (Vitter's Algorithm R) — exact percentiles over a bounded memory
    footprint. Deterministically seeded so snapshots are reproducible
    under test."""

    def __init__(self, capacity=1024, seed=0x5EED):
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.capacity = int(capacity)
        self._rng = random.Random(seed)
        self._samples = []
        self._seen = 0
        self._lock = threading.Lock()

    def add(self, value):
        v = float(value)
        with self._lock:
            self._seen += 1
            if len(self._samples) < self.capacity:
                self._samples.append(v)
            else:
                j = self._rng.randrange(self._seen)
                if j < self.capacity:
                    self._samples[j] = v

    @property
    def seen(self):
        return self._seen

    def samples(self):
        with self._lock:
            return tuple(self._samples)

    def percentile(self, q):
        """Linear-interpolated percentile over the current sample,
        q in [0, 100]; None when empty."""
        with self._lock:
            xs = sorted(self._samples)
        return _interp_percentile(xs, q)


def _interp_percentile(xs, q):
    """Linear-interpolated percentile of a sorted list; None if empty."""
    if not xs:
        return None
    if len(xs) == 1:
        return xs[0]
    pos = (float(q) / 100.0) * (len(xs) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(xs) - 1)
    frac = pos - lo
    return xs[lo] * (1.0 - frac) + xs[hi] * frac


# ------------------------------------------------ scrape-merge support
# The federation layer (observability.fleet) aggregates MANY replica
# registries from their scraped ``snapshot()`` JSON. Merging lives
# here, next to the exposition format it inverts: counters/gauges sum,
# histograms merge BUCKET-WISE (every engine histogram is fixed-bucket
# by construction, so bucket counts are additive and fleet percentiles
# come from the merged distribution — never from averaged per-replica
# percentiles, which is statistically meaningless).

def _bucket_bound(le):
    return float("inf") if le == "+Inf" else float(le)


def merge_histogram_snapshots(entries):
    """Merge snapshot-format histogram dicts (``{count, sum, buckets:
    {le: cumulative}}``) bucket-wise: counts and sums add, cumulative
    bucket counts add per ``le`` bound. Entries with different bucket
    layouts merge over the UNION of bounds (a missing bound inherits
    the entry's nearest lower cumulative count — exact for the
    fixed-bucket families this stack emits, conservative otherwise).
    Returns the same shape; ``None``/empty input merges to a zero
    histogram."""
    entries = [e for e in (entries or []) if e]
    bounds = sorted({b for e in entries for b in e.get("buckets", {})},
                    key=_bucket_bound)
    if "+Inf" not in bounds:
        bounds.append("+Inf")
    merged = {le: 0 for le in bounds}
    total_count = 0
    total_sum = 0.0
    for e in entries:
        total_count += int(e.get("count", 0))
        total_sum += float(e.get("sum", 0.0))
        ebuckets = sorted(e.get("buckets", {}).items(),
                          key=lambda kv: _bucket_bound(kv[0]))
        for le in bounds:
            cum = 0
            bound = _bucket_bound(le)
            for ele, ecum in ebuckets:
                if _bucket_bound(ele) <= bound:
                    cum = ecum
                else:
                    break
            if le == "+Inf":
                cum = int(e.get("count", 0))
            merged[le] += int(cum)
    return {"count": total_count, "sum": round(total_sum, 6),
            "buckets": merged}


def percentile_from_buckets(buckets, q):
    """Percentile estimate from cumulative fixed buckets (``{le:
    cumulative}``), Prometheus ``histogram_quantile`` style: find the
    bucket the q-quantile rank lands in and interpolate linearly
    inside it. The +Inf bucket clamps to the largest finite bound (no
    invented upper edge). None when empty."""
    if not buckets:
        return None
    items = sorted(buckets.items(), key=lambda kv: _bucket_bound(kv[0]))
    total = items[-1][1]
    if not total:
        return None
    target = (float(q) / 100.0) * total
    prev_cum, prev_bound = 0, 0.0
    largest_finite = max((_bucket_bound(le) for le, _ in items
                          if le != "+Inf"), default=0.0)
    for le, cum in items:
        bound = _bucket_bound(le)
        if cum >= target:
            if bound == float("inf"):
                return largest_finite
            in_bucket = cum - prev_cum
            frac = ((target - prev_cum) / in_bucket) if in_bucket else 1.0
            return prev_bound + frac * (bound - prev_bound)
        prev_cum, prev_bound = cum, (bound if bound != float("inf")
                                     else prev_bound)
    return largest_finite


def _parse_series_key(key):
    """Invert the snapshot series key format ('k=v,k=v', '' for
    unlabeled) back into label pairs. Exact for every label value this
    stack emits (program keys, detector names, span scopes, shed
    reasons — none contain ',' or '='); foreign values containing
    either would split lossily, which the fleet exposition accepts."""
    if not key:
        return []
    pairs = []
    for part in key.split(","):
        k, _, v = part.partition("=")
        pairs.append((k, v))
    return pairs


def prometheus_text_from_snapshots(labeled_snapshots,
                                   label="replica"):
    """Render MANY registry ``snapshot()`` dicts as ONE Prometheus
    text exposition, stamping each snapshot's series with an extra
    ``label`` (default ``replica``) — the scrape-merge step of the
    fleet federation surface (``/fleet/metrics``): per-replica series
    stay distinct (Prometheus-federation style), and any downstream
    aggregation can sum/merge them knowing which replica each sample
    came from. ``labeled_snapshots`` is an iterable of
    ``(label_value, snapshot_dict)``."""
    labeled = [(str(lv), snap or {}) for lv, snap in labeled_snapshots]
    names = sorted({n for _, snap in labeled for n in snap})
    lines = []
    for name in names:
        fams = [(lv, snap[name]) for lv, snap in labeled
                if name in snap]
        kind = fams[0][1].get("type", "gauge")
        help_text = next((f.get("help") for _, f in fams
                          if f.get("help")), "")
        if help_text:
            lines.append(f"# HELP {name} {_escape_help(help_text)}")
        lines.append(f"# TYPE {name} {kind}")
        for lv, fam in fams:
            if fam.get("type", kind) != kind:
                continue      # kind clash across replicas: skip, never 500
            for key in sorted(fam.get("values", {})):
                value = fam["values"][key]
                pairs = [(label, lv)] + _parse_series_key(key)
                body = ",".join(f'{k}="{_escape_label(v)}"'
                                for k, v in pairs)
                if kind == "histogram" and isinstance(value, dict):
                    buckets = sorted(
                        value.get("buckets", {}).items(),
                        key=lambda kv: _bucket_bound(kv[0]))
                    for le, cum in buckets:
                        lines.append(
                            f'{name}_bucket{{{body},le='
                            f'"{_escape_label(le)}"}} {_fmt(cum)}')
                    lines.append(f"{name}_sum{{{body}}} "
                                 f"{_fmt(value.get('sum', 0.0))}")
                    lines.append(f"{name}_count{{{body}}} "
                                 f"{_fmt(value.get('count', 0))}")
                else:
                    try:
                        sample = _fmt(value)
                    except (TypeError, ValueError):
                        continue
                    lines.append(f"{name}{{{body}}} {sample}")
    return "\n".join(lines) + "\n"


class WindowedReservoir:
    """Sliding-TIME-window observation buffer: percentiles over the
    last ``window_s`` seconds of traffic instead of process lifetime
    (the uniform Reservoir above never forgets — a latency spike from
    an hour ago still shapes its p99). Bounded two ways: observations
    older than the window are pruned at every add/read, and the buffer
    never holds more than ``capacity`` points (burst overflow drops
    the OLDEST — the window stays recency-faithful).

    ``clock`` is injectable (tests drive a fake monotonic clock); an
    explicit ``now=`` on any method overrides it per call.
    """

    def __init__(self, window_s=60.0, capacity=4096,
                 clock=time.monotonic):
        if window_s <= 0:
            raise ValueError("window_s must be > 0")
        if capacity < 1:
            raise ValueError("capacity must be >= 1")
        self.window_s = float(window_s)
        self.capacity = int(capacity)
        self._clock = clock
        self._buf = collections.deque()   # (t, value), t ascending
        self._seen = 0
        self._lock = threading.Lock()

    def _prune(self, now):
        cutoff = now - self.window_s
        while self._buf and self._buf[0][0] < cutoff:
            self._buf.popleft()

    def add(self, value, now=None):
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._seen += 1
            self._prune(now)
            if len(self._buf) == self.capacity:
                self._buf.popleft()
            self._buf.append((now, float(value)))

    @property
    def seen(self):
        """Observations ever added (window pruning doesn't unsee)."""
        return self._seen

    def values(self, now=None):
        now = self._clock() if now is None else float(now)
        with self._lock:
            self._prune(now)
            return [v for _, v in self._buf]

    def count(self, now=None):
        return len(self.values(now))

    def percentile(self, q, now=None):
        """Linear-interpolated percentile over the CURRENT window,
        q in [0, 100]; None when the window is empty."""
        return _interp_percentile(sorted(self.values(now)), q)


class MetricsRegistry:
    """Named families, one namespace; snapshot() and prometheus_text()
    are the two exposition surfaces (JSON artifact / scrape)."""

    def __init__(self, max_label_values=128):
        self._lock = threading.RLock()
        self._families = {}
        # per-family distinct-label-value cap (0 disables): generous
        # enough that every legitimate family in this stack (span
        # scopes, detectors, shed reasons, bounded tenant ids) never
        # folds, small enough that an adversarial flood can't blow up
        # the registry — overflow folds into OVERFLOW_LABEL and counts
        # in metrics_label_overflow_total{family}
        self.max_label_values = int(max_label_values)

    def _register(self, cls, name, help_text, labelnames, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != cls.kind or \
                        fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} re-registered as a different "
                        f"kind/labelset")
                return fam
            fam = cls(self, name, help_text, labelnames, **kw)
            self._families[name] = fam
            return fam

    def counter(self, name, help_text="", labelnames=()):
        return self._register(Counter, name, help_text, labelnames)

    def gauge(self, name, help_text="", labelnames=()):
        return self._register(Gauge, name, help_text, labelnames)

    def histogram(self, name, help_text="", labelnames=(),
                  buckets=DEFAULT_TIME_BUCKETS):
        return self._register(Histogram, name, help_text, labelnames,
                              buckets=buckets)

    def scrape_error(self, metric_name):
        """Record one gauge pull-callback failure at scrape/snapshot
        time (the series exported NaN instead of 500ing the whole
        exposition). The ``metrics_scrape_errors_total{metric}``
        counter is registered LAZILY on the first failure, so a clean
        registry exposes no error family at all."""
        self.counter(
            "metrics_scrape_errors_total",
            "gauge set_function callbacks that raised at scrape time "
            "(the series exported NaN; the exposition survived)",
            labelnames=("metric",)).labels(str(metric_name)).inc()

    def label_overflow(self, family_name):
        """Record one over-cardinality label fold (see _Family.labels).
        The ``metrics_label_overflow_total{family}`` counter is
        registered LAZILY on the first fold, so a registry that never
        overflows exposes no overflow family at all."""
        self.counter(
            "metrics_label_overflow_total",
            "label-value tuples folded into the ~other overflow "
            "series because the family hit max_label_values",
            labelnames=("family",)).labels(str(family_name)).inc()

    def get(self, name):
        with self._lock:
            return self._families.get(name)

    def families(self):
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # ---------------------------------------------------- exposition
    def snapshot(self):
        """Stable, JSON-serializable view: family name -> {type, help,
        values} with label series keyed 'k=v,k=v' ('' for unlabeled)."""
        out = {}
        for fam in self.families():
            values = {}
            for labelvalues, child in fam.series():
                key = ",".join(f"{k}={v}" for k, v in
                               zip(fam.labelnames, labelvalues))
                if fam.kind == "histogram":
                    values[key] = {
                        "count": child.count,
                        "sum": round(child.sum, 6),
                        "buckets": dict(child.cumulative_buckets()),
                    }
                else:
                    values[key] = child.value
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "values": values}
        return out

    def snapshot_json(self):
        return json.dumps(self.snapshot(), sort_keys=True)

    def prometheus_text(self):
        """Prometheus text exposition format 0.0.4: HELP/TYPE lines,
        escaped label values, cumulative histogram buckets with the
        canonical _bucket/_sum/_count triple."""
        lines = []
        for fam in self.families():
            if fam.help:
                lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for labelvalues, child in fam.series():
                pairs = [f'{k}="{_escape_label(v)}"' for k, v in
                         zip(fam.labelnames, labelvalues)]
                base = "{" + ",".join(pairs) + "}" if pairs else ""
                if fam.kind == "histogram":
                    for le, cum in child.cumulative_buckets():
                        bpairs = pairs + [f'le="{le}"']
                        lines.append(f"{fam.name}_bucket{{"
                                     + ",".join(bpairs) + f"}} {cum}")
                    lines.append(f"{fam.name}_sum{base} "
                                 f"{_fmt(child.sum)}")
                    lines.append(f"{fam.name}_count{base} "
                                 f"{child.count}")
                else:
                    lines.append(f"{fam.name}{base} {_fmt(child.value)}")
        return "\n".join(lines) + "\n"


_default_registry = MetricsRegistry()


def default_registry():
    """The process-global registry profiler.record_scope accrues into
    (span seconds + span count per scope name)."""
    return _default_registry


class MetricsServerHandle:
    """Cleanly-stoppable handle for a running metrics HTTP server:
    ``close()`` is idempotent (shutdown + socket close + thread join),
    the handle is a context manager, and the legacy server surface
    (``server_address``, ``shutdown()``) is preserved so existing
    callers keep working. The serving engine tracks every handle it
    hands out and closes them in ``ServingEngine.close()`` — the
    daemon thread no longer leaks across tests."""

    def __init__(self, server, thread):
        self._server = server
        self._thread = thread
        self._lock = threading.Lock()
        self._closed = False

    @property
    def server_address(self):
        return self._server.server_address

    @property
    def port(self):
        return self._server.server_address[1]

    @property
    def url(self):
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    @property
    def closed(self):
        return self._closed

    def close(self):
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)

    def shutdown(self):  # legacy alias (pre-handle callers)
        self.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False


def start_metrics_server(registry=None, port=0, addr="127.0.0.1",
                         extra_routes=None, post_routes=None,
                         max_body_bytes=1 << 20):
    """Serve ``/metrics`` (Prometheus text) and ``/metrics.json`` (the
    snapshot) on a stdlib HTTP server in a daemon thread.
    ``extra_routes`` maps additional paths to zero-arg callables: a
    JSON-serializable return value is served as application/json (the
    serving engine mounts ``/debug/requests`` and ``/debug/state``
    this way), a ``str`` return value is served as Prometheus-flavored
    text/plain (the fleet server mounts its merged ``/fleet/metrics``
    exposition this way). ``GET /debug`` serves the route index
    ({"routes": [every mounted path]}) so operators can discover the
    surface without reading source (an explicit ``/debug`` extra
    route overrides the built-in index). Every route — the built-in
    /metrics pair included — renders its FULL body before any byte
    goes on the wire, and a rendering failure turns into a 500, so a
    scraper racing an engine shutdown reads either a complete
    response or a clean error, never a truncated half-body.

    ``post_routes`` maps paths to one-arg callables receiving the
    request's parsed-JSON body (the serving gateway mounts
    ``POST /v1/generate`` this way). The callable returns either a
    payload (served as 200 application/json) or a ``(status,
    payload)`` tuple for explicit status codes (e.g. 503 while
    draining). The wire contract is defensive by construction: a body
    over ``max_body_bytes`` is refused with 413 before it is read, a
    missing/oversized-or-absent Content-Length is a 411/413, malformed
    JSON (or a non-object body) is a 400 with a JSON error envelope —
    never a traceback — and a handler exception is a clean 500.

    Returns a MetricsServerHandle: ``handle.port`` is the bound port
    (``port=0`` picks a free one), ``handle.close()`` stops it
    (idempotent; also a context manager)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    reg = registry if registry is not None else default_registry()
    routes = dict(extra_routes or {})
    posts = dict(post_routes or {})
    if "/debug" not in routes:
        index = sorted(["/metrics", "/metrics.json", "/debug"]
                       + list(routes) + list(posts))
        routes["/debug"] = lambda: {"routes": index}

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, status, payload):
            body = json.dumps(payload, sort_keys=True).encode("utf-8")
            try:
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError, OSError):
                pass

        def do_POST(self):
            path = self.path.split("?", 1)[0].rstrip("/")
            handler = posts.get(path)
            if handler is None:
                self.send_error(405 if path in routes else 404)
                return
            try:
                length = int(self.headers.get("Content-Length", ""))
            except ValueError:
                self._reply(411, {"error": "Content-Length required"})
                return
            if length > max_body_bytes:
                self._reply(413, {
                    "error": "body too large",
                    "max_body_bytes": max_body_bytes})
                return
            raw = self.rfile.read(max(0, length))
            try:
                body = json.loads(raw.decode("utf-8"))
                if not isinstance(body, dict):
                    raise ValueError("body must be a JSON object")
            except Exception as e:  # noqa: BLE001 - 400, no traceback
                self._reply(400, {
                    "error": "malformed JSON body",
                    "detail": f"{type(e).__name__}: {e}"[:200]})
                return
            try:
                out = handler(body)
            except Exception as e:  # noqa: BLE001 - 500, no traceback
                self._reply(500, {
                    "error": f"{type(e).__name__}: {e}"[:200]})
                return
            if (isinstance(out, tuple) and len(out) == 2
                    and isinstance(out[0], int)):
                self._reply(out[0], out[1])
            else:
                self._reply(200, out)

        def do_GET(self):
            path, _, query = self.path.partition("?")
            path = path.rstrip("/") or "/metrics"
            try:
                if path == "/metrics":
                    body = reg.prometheus_text().encode("utf-8")
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                elif path == "/metrics.json":
                    body = reg.snapshot_json().encode("utf-8")
                    ctype = "application/json"
                elif path in routes:
                    fn = routes[path]
                    if getattr(fn, "accepts_query", False):
                        # a route opting into query params (e.g. the
                        # engine's /debug/requests?tenant= filter)
                        # receives {param: last_value}
                        from urllib.parse import parse_qs
                        params = {k: v[-1] for k, v in
                                  parse_qs(query).items()}
                        payload = fn(params)
                    else:
                        payload = fn()
                    if isinstance(payload, str):
                        body = payload.encode("utf-8")
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    else:
                        body = json.dumps(
                            payload, sort_keys=True).encode("utf-8")
                        ctype = "application/json"
                else:
                    self.send_error(404)
                    return
            except Exception as e:  # noqa: BLE001 - 500, never half-body
                try:
                    self.send_error(500, f"{type(e).__name__}: {e}")
                except Exception:   # peer already gone mid-shutdown
                    pass
                return
            try:
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError, OSError):
                # the scraper hung up (or the server is closing the
                # socket under us mid-shutdown): nothing to answer
                pass

        def log_message(self, *args):  # silence per-request stderr spam
            pass

    server = ThreadingHTTPServer((addr, port), Handler)
    thread = threading.Thread(target=server.serve_forever, daemon=True,
                              name="paddle-tpu-metrics")
    thread.start()
    return MetricsServerHandle(server, thread)
