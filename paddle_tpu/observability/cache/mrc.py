"""Reuse-distance sampling + miss-ratio-curve (MRC) estimation for the
paged KV block economy.

"How big should the cache be" is a reuse-distance question: an access
to a block whose LRU stack distance is d hits any cache of capacity
> d, so the distance histogram IS the hit-rate-vs-capacity curve. The
exact histogram needs the full LRU stack (one entry per distinct block
path ever seen) — fine for tests, unbounded online. The online
sampler uses SHARDS-style SPATIAL sampling (Waldspurger et al.,
FAST'15): keep only block paths whose stable hash lands under a
threshold (rate R), track exact distances WITHIN the sampled
population, and scale distances by 1/R. Hit-rate estimates then come
from sampled counts alone (both numerator and denominator are sampled
at the same rate, so no count rescaling is needed).

Bounded three ways: the sampled population is capped (oldest sampled
path dropped, later re-accesses count cold — a conservative bias
toward predicting misses), scaled distances beyond ``max_distance``
lump into one overflow bucket (they are misses at every capacity we
would ever evaluate), and the histogram itself is keyed by scaled
distance, at most one bucket per tracked path.

``exact_mrc`` is the oracle the estimator is validated against
in-tree (tests/test_cache.py) and the sizing tool for small offline
traces; the estimator is the production path.
"""
import collections

__all__ = ["ReuseDistanceSampler", "exact_mrc", "merge_mrc_points"]

# Knuth multiplicative hash: spreads sequential fingerprints uniformly
# over 32 bits so "hash < rate * 2^32" is an unbiased spatial sample
_HASH_MULT = 2654435761
_HASH_MOD = 1 << 32


def _spatial_hash(obj):
    return (int(obj) * _HASH_MULT) % _HASH_MOD


class ReuseDistanceSampler:
    """Spatially-sampled reuse-distance histogram over an access
    stream of integer object ids (stable block-path fingerprints).

    ``record(obj)`` per access; ``est_hit_rate(capacity)`` /
    ``mrc(capacities)`` to read the curve. ``rate=1.0`` degenerates to
    the exact (unsampled) histogram — the property tests pin that
    equivalence against ``exact_mrc``.
    """

    def __init__(self, rate=0.125, max_tracked=2048,
                 max_distance=1 << 16):
        if not (0.0 < rate <= 1.0):
            raise ValueError(f"rate must be in (0, 1], got {rate}")
        if max_tracked < 1:
            raise ValueError("max_tracked must be >= 1")
        self.rate = float(rate)
        self.max_tracked = int(max_tracked)
        self.max_distance = int(max_distance)
        self._threshold = int(self.rate * _HASH_MOD)
        # sampled population in LRU order (most recent LAST); value
        # unused — the OrderedDict is the recency stack
        self._last = collections.OrderedDict()
        self._hist = {}          # scaled distance -> sampled accesses
        self.cold = 0            # sampled first-touches (infinite d)
        self.overflow = 0        # sampled reuses at d >= max_distance
        self.reuses = 0          # sampled reuses binned in _hist
        self.dropped = 0         # sampled paths aged out of tracking

    # ----------------------------------------------------- recording
    def sampled(self, obj):
        return _spatial_hash(obj) < self._threshold

    def record(self, obj):
        """One access to ``obj``. Non-sampled objects return
        immediately (the common case at low rates — this is the whole
        overhead story of the sampler)."""
        if _spatial_hash(obj) >= self._threshold:
            return
        last = self._last
        if obj in last:
            # exact stack distance within the sampled population:
            # walk back from the most recent entry. O(distance), and
            # hot paths (the ones that matter) have SMALL distances.
            d = 0
            for o in reversed(last):
                if o == obj:
                    break
                d += 1
            last.move_to_end(obj)
            scaled = int(d / self.rate)
            if scaled >= self.max_distance:
                self.overflow += 1
            else:
                self.reuses += 1
                self._hist[scaled] = self._hist.get(scaled, 0) + 1
        else:
            self.cold += 1
            last[obj] = None
            if len(last) > self.max_tracked:
                last.popitem(last=False)
                self.dropped += 1

    # ----------------------------------------------------- estimates
    @property
    def sampled_accesses(self):
        return self.cold + self.overflow + self.reuses

    @property
    def tracked(self):
        return len(self._last)

    def est_hit_rate(self, capacity_blocks):
        """Estimated hit rate of an LRU cache holding
        ``capacity_blocks`` blocks: the fraction of sampled accesses
        whose scaled reuse distance fits. None before any sampled
        traffic."""
        total = self.sampled_accesses
        if not total:
            return None
        cap = int(capacity_blocks)
        hits = sum(n for d, n in self._hist.items() if d < cap)
        return hits / total

    def mrc(self, capacities):
        """[{"blocks": C, "est_hit_rate": r}] for each capacity, in
        one cumulative pass over the histogram (sorted distances)."""
        caps = sorted(int(c) for c in capacities)
        total = self.sampled_accesses
        out = []
        if not total:
            return [{"blocks": c, "est_hit_rate": None} for c in caps]
        dists = sorted(self._hist.items())
        i, cum = 0, 0
        for cap in caps:
            while i < len(dists) and dists[i][0] < cap:
                cum += dists[i][1]
                i += 1
            out.append({"blocks": cap,
                        "est_hit_rate": round(cum / total, 6)})
        return out

    def report(self):
        """The ``sampled`` sub-dict of the cache report (bounded:
        scalar counters only — the MRC curve carries the histogram's
        information at the capacities that matter)."""
        return {
            "rate": self.rate,
            "accesses": self.sampled_accesses,
            "cold": self.cold,
            "overflow": self.overflow,
            "tracked": self.tracked,
            "dropped": self.dropped,
        }


def exact_mrc(trace, capacities):
    """Exact LRU hit rate per capacity over a full access trace, one
    pass (the validation oracle: unbounded state, offline only).
    Returns {capacity: hit_rate-or-None-when-empty}."""
    caps = [int(c) for c in capacities]
    last = collections.OrderedDict()
    hits = {c: 0 for c in caps}
    total = 0
    for obj in trace:
        total += 1
        if obj in last:
            d = 0
            for o in reversed(last):
                if o == obj:
                    break
                d += 1
            last.move_to_end(obj)
            for c in caps:
                if d < c:
                    hits[c] += 1
        else:
            last[obj] = None
    if not total:
        return {c: None for c in caps}
    return {c: hits[c] / total for c in caps}


def merge_mrc_points(point_lists, weights):
    """Fleet-exact merge of per-replica MRC curves: at each capacity
    the fleet estimate is the access-weighted mean of replica
    estimates — algebraically identical to pooling the replicas'
    sampled histograms, so the merge is exact, never an average of
    averages with equal weights. Capacities present in every replica
    survive; None estimates (no traffic yet) contribute zero weight."""
    common = None
    for pts in point_lists:
        caps = {p["blocks"] for p in (pts or [])}
        common = caps if common is None else (common & caps)
    if not common:
        return []
    out = []
    for cap in sorted(common):
        num = den = 0.0
        for pts, w in zip(point_lists, weights):
            est = next(p["est_hit_rate"] for p in pts
                       if p["blocks"] == cap)
            if est is None or not w:
                continue
            num += est * float(w)
            den += float(w)
        out.append({"blocks": cap,
                    "est_hit_rate": round(num / den, 6) if den
                    else None})
    return out
