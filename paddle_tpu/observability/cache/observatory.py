"""The cache observatory: one attach point on the paged KV pool that
turns the block economy's raw events into operator answers —

  * "how big should the cache be"  -> reuse-distance sampler + MRC
    (mrc.ReuseDistanceSampler; ROADMAP-#5's spill-tier sizing tool);
  * "which prefixes are hot"       -> per-node heat digest
    (heat.top_prefix_digest over the radix index's hit counters;
    ROADMAP-#2's router affinity signal);
  * "what is the cache worth"      -> per-request savings attribution
    (cached tokens x measured per-token prefill cost from the PR-10
    perf observatory -> estimated TTFT ms saved);
  * "is eviction thrashing"        -> block-lifetime reservoir +
    the radix eviction-then-reinsert counter.

Observatory playbook (PR 8/10/11): every structure bounded, hooks are
a few dict/int ops on the admission path (probe-measured in the bench
artifact's ``shared_prefix.cache.overhead`` section), the report is
schema-pinned (``CACHE_KEYS``), disabled engines report the same key
set (``disabled_cache_report``), and the class survives a supervisor
pool swap (``attach_pool`` re-points every pull source at the new
pool; counters and the sampler keep their history — a restart does
not forget the workload).
"""
import time

from .heat import top_prefix_digest
from .mrc import ReuseDistanceSampler
from ..registry import Reservoir

__all__ = ["CacheObservatory", "disabled_cache_report", "CACHE_KEYS",
           "MRC_CAPACITY_FACTORS"]

# snapshot()["cache"] schema contract (additions only, never renames)
CACHE_KEYS = (
    "enabled", "accesses", "hits", "hit_rate", "capacity_blocks",
    "sampled", "mrc", "heat", "savings", "churn",
)

# the capacities the MRC is evaluated at, as multiples of the pool's
# current usable capacity — 0.5x/1x answer "could we shrink", 2x/4x
# answer ROADMAP-#5's "what would a host-RAM spill tier buy"
MRC_CAPACITY_FACTORS = (0.5, 1.0, 2.0, 4.0)

_PREFILL_KINDS = ("prefill", "paged_prefill", "chunk_prefill")


def disabled_cache_report():
    """The ``snapshot()["cache"]`` section of an engine without a
    cache observatory (cache=False, or a legacy non-paged pool) —
    same key set as a live report, so the snapshot schema contract
    holds either way."""
    return {"enabled": False, "accesses": 0, "hits": 0,
            "hit_rate": None, "capacity_blocks": None, "sampled": None,
            "mrc": None, "heat": None, "savings": None, "churn": None}


class CacheObservatory:
    """Registry-backed cache telemetry, attached to a PagedKVPool via
    ``attach_pool`` (which sets itself as ``pool.observer``).
    ``enabled=False`` registers nothing and every hook no-ops."""

    LIFETIME_RESERVOIR = 1024
    HEAT_TOP_K = 8

    def __init__(self, registry, enabled=True, sample_rate=0.125,
                 heat_top_k=None, clock=time.perf_counter):
        self.enabled = bool(enabled)
        self._pool = None
        if not self.enabled:
            return
        self._clock = clock
        self.heat_top_k = int(heat_top_k or self.HEAT_TOP_K)
        self.sampler = ReuseDistanceSampler(rate=sample_rate)
        # exact (unsampled) block-access counters: the measured hit
        # rate the MRC estimate at 1x capacity is judged against
        self.accesses = 0
        self.hits = 0
        # savings attribution state (per-token cost joined lazily from
        # the perf observatory via bind_cost_source)
        self._perf = None
        self._computed_tokens_fn = None
        self._birth = {}          # block -> clock() at allocation
        self._lifetimes = Reservoir(self.LIFETIME_RESERVOIR)
        self._h_lifetime = registry.histogram(
            "serving_cache_block_lifetime_seconds",
            "allocation -> free/eviction wall seconds per KV block "
            "(evictable parking time counts as alive: the block is "
            "still serving hits)")
        self._c_saved_tokens = registry.counter(
            "serving_cache_saved_tokens_total",
            "prompt tokens served from the prefix cache (the savings "
            "attribution numerator; mirrors "
            "serving_prefix_cached_tokens_total at admission points "
            "the observatory sees)")
        self._c_saved_ms = registry.counter(
            "serving_cache_saved_ttft_ms_total",
            "estimated TTFT milliseconds saved by prefix-cache hits: "
            "cached tokens x measured per-token prefill wall (perf "
            "observatory join; accrues 0 until prefill measurements "
            "exist)")
        # pull gauges read THROUGH self so a supervisor pool swap
        # re-points them automatically (attach_pool only sets _pool)
        registry.gauge(
            "serving_cache_block_accesses_total",
            "block-granular prefix-cache accesses (full prompt blocks "
            "probed at admission)"
        ).set_function(lambda: float(self.accesses))
        registry.gauge(
            "serving_cache_block_hits_total",
            "block-granular prefix-cache hits (prompt blocks found "
            "cached at admission)"
        ).set_function(lambda: float(self.hits))
        registry.gauge(
            "serving_cache_thrash_reinserts_total",
            "evicted-then-reinserted radix paths (each one is a block "
            "the cache gave up and then recomputed — sustained growth "
            "means the pool is too small for the working set)"
        ).set_function(self._thrash_count)

    # ------------------------------------------------------- wiring
    def attach_pool(self, pool):
        """Point the observatory at a (possibly new) pool and make it
        the pool's event observer. Called at engine construction and
        again after a supervisor restart swaps the pool — history
        (sampler, savings, lifetime reservoir) survives the swap."""
        if not self.enabled:
            return
        self._pool = pool
        pool.observer = self

    def bind_cost_source(self, perf, computed_tokens_fn):
        """Join the PR-10 perf observatory: per-token prefill cost =
        measured prefill-family wall seconds over prefill-computed
        tokens (both live accumulators, read at attribution time)."""
        if not self.enabled:
            return
        self._perf = perf
        self._computed_tokens_fn = computed_tokens_fn

    def _thrash_count(self):
        pool = self._pool
        return float(pool.index.thrash_count) if pool is not None \
            else 0.0

    # ------------------------------------------------ pool callbacks
    # (hot path: a dict store / pop and a few int ops per block event;
    # the sampler's spatial filter rejects most accesses in O(1))
    def on_block_alloc(self, block):
        self._birth[block] = self._clock()

    def on_block_free(self, block, evicted):
        t0 = self._birth.pop(block, None)
        if t0 is not None:
            dt = self._clock() - t0
            self._lifetimes.add(dt)
            self._h_lifetime.observe(dt)

    def on_admission(self, fps, n_hit):
        """One admission's block-granular prefix probe: ``fps`` are
        the stable path fingerprints of the prompt's full blocks (in
        path order), ``n_hit`` how many were found cached."""
        self.accesses += len(fps)
        self.hits += int(n_hit)
        record = self.sampler.record
        for fp in fps:
            record(fp)

    # --------------------------------------------------- attribution
    def per_token_prefill_ms(self):
        """Measured per-token prefill cost in ms: prefill-family
        program wall (dispatch + sync) over prefill-computed tokens.
        None until both sides have data — early admissions attribute
        no savings rather than invented ones."""
        if self._perf is None or self._computed_tokens_fn is None:
            return None
        tokens = self._computed_tokens_fn()
        if not tokens:
            return None
        wall_s = self._perf.prefill_seconds()
        if not wall_s:
            return None
        return wall_s / float(tokens) * 1000.0

    def estimate_saved_ms(self, cached_tokens):
        """Estimated TTFT ms a prefix hit of ``cached_tokens`` saves,
        WITHOUT accruing it (the flight-recorder detail is stamped at
        dispatch time; the counter accrues once, in note_reuse)."""
        if not self.enabled or not cached_tokens:
            return None
        per_ms = self.per_token_prefill_ms()
        if per_ms is None:
            return None
        return int(cached_tokens) * per_ms

    def note_reuse(self, cached_tokens):
        """One admission's savings: called with the cached-token count
        at the same point ServingMetrics.record_prefix_reuse accounts
        it. Returns the estimated ms saved (None before the perf join
        has data) so the engine can stamp it onto the flight-recorder
        prefix_hit detail."""
        if not self.enabled or not cached_tokens:
            return None
        self._c_saved_tokens.inc(int(cached_tokens))
        per_ms = self.per_token_prefill_ms()
        if per_ms is None:
            return None
        saved = int(cached_tokens) * per_ms
        self._c_saved_ms.inc(saved)
        return saved

    # ----------------------------------------------------- reporting
    def measured_hit_rate(self):
        return self.hits / self.accesses if self.accesses else None

    def mrc_points(self, capacity_blocks=None):
        """The MRC evaluated at MRC_CAPACITY_FACTORS multiples of the
        pool's usable capacity (trash block excluded), each point
        carrying its factor so readers need no division."""
        if capacity_blocks is None:
            pool = self._pool
            if pool is None:
                return None
            capacity_blocks = pool.num_blocks - 1
        caps = [max(1, int(round(capacity_blocks * f)))
                for f in MRC_CAPACITY_FACTORS]
        points = self.sampler.mrc(caps)
        for pt, f in zip(points, MRC_CAPACITY_FACTORS):
            pt["factor"] = f
        return points

    def report(self):
        """The ``snapshot()["cache"]`` / ``/debug/cache`` body (key
        set pinned by tests/test_observability.py)."""
        if not self.enabled or self._pool is None:
            return disabled_cache_report()
        pool = self._pool
        cap = pool.num_blocks - 1
        entries = pool.index.heat_entries()
        heat = top_prefix_digest(entries, k=self.heat_top_k)
        hit_rate = self.measured_hit_rate()
        per_ms = self.per_token_prefill_ms()
        life = {"count": self._lifetimes.seen}
        for q, key in ((50, "p50_ms"), (90, "p90_ms"), (99, "p99_ms")):
            p = self._lifetimes.percentile(q)
            life[key] = None if p is None else round(p * 1000.0, 3)
        return {
            "enabled": True,
            "accesses": self.accesses,
            "hits": self.hits,
            "hit_rate": round(hit_rate, 4) if hit_rate is not None
            else None,
            "capacity_blocks": cap,
            "sampled": self.sampler.report(),
            "mrc": self.mrc_points(cap),
            "heat": {
                "top": heat,
                "indexed_blocks": len(pool.index),
                "total_hits": sum(e["hits"] for e in entries),
            },
            "savings": {
                "saved_tokens": int(self._c_saved_tokens.value),
                "saved_ttft_ms": round(self._c_saved_ms.value, 3),
                "per_token_prefill_ms": round(per_ms, 6)
                if per_ms is not None else None,
            },
            "churn": {
                "evictions": pool.evictions,
                "thrash_reinserts": pool.index.thrash_count,
                "block_lifetime_ms": life,
            },
        }
