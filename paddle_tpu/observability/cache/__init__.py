"""Cache observatory (PR 13): reuse-distance/MRC profiling, prefix
heat analytics, per-request cache-savings attribution, and eviction-
churn telemetry for the paged KV block economy.

Three modules, one attach point:

  * ``mrc``         — SHARDS-style spatially-sampled reuse-distance
                      histogram + miss-ratio-curve estimation, with
                      the exact small-trace simulator it is validated
                      against (``exact_mrc``) and the fleet-exact
                      curve merge (``merge_mrc_points``);
  * ``heat``        — top-K hot-prefix digest over the radix index's
                      per-node hit/tick/tokens-saved counters, and
                      its fleet merge (``merge_heat_digests``);
  * ``observatory`` — CacheObservatory: the PagedKVPool observer that
                      feeds all of the above plus block-lifetime and
                      TTFT-savings accounting, reported as the
                      schema-pinned ``snapshot()["cache"]`` /
                      ``/debug/cache`` body (``CACHE_KEYS``,
                      ``disabled_cache_report``).
"""
from .heat import (  # noqa: F401
    merge_heat_digests, top_prefix_digest,
)
from .mrc import (  # noqa: F401
    ReuseDistanceSampler, exact_mrc, merge_mrc_points,
)
from .observatory import (  # noqa: F401
    CACHE_KEYS, CacheObservatory, MRC_CAPACITY_FACTORS,
    disabled_cache_report,
)
