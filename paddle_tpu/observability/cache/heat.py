"""Prefix heat analytics: which cached prefixes earn their blocks.

The radix index already stamps per-node LRU ticks; the cache
observatory adds per-node hit counts, and this module rolls both into
the top-K HOT-PREFIX digest — the signal ROADMAP direction #2's
prefix-affinity router needs: "requests matching fingerprint F save
T tokens here", without ever shipping raw prompt tokens (the
fingerprint is a stable 32-bit hash of the token path, computed by
serving.paged.radix.path_fingerprint).

Digest entries are JSON-scalar only and the digest is top-K bounded,
so it rides along in ``snapshot()["cache"]`` and the fleet state body
for free. ``merge_heat_digests`` is the fleet rollup rule: entries
combine BY FINGERPRINT (hits and tokens-saved sum exactly — the same
prefix hot on two replicas is one fleet-wide prefix), then the merged
set is re-ranked and re-truncated to K.
"""

__all__ = ["top_prefix_digest", "merge_heat_digests"]


def top_prefix_digest(entries, k=8):
    """Rank per-node heat entries (dicts with fp/depth/hits/last_tick/
    tokens_saved, as produced by RadixPrefixIndex.heat_entries) and
    keep the top ``k`` by tokens saved; fingerprint breaks ties so the
    digest is deterministic."""
    ranked = sorted(
        (e for e in entries if e.get("hits")),
        key=lambda e: (-e["tokens_saved"], -e["hits"], e["fp"]))
    return [dict(e) for e in ranked[:int(k)]]


def merge_heat_digests(digests, k=8):
    """Exact fleet merge of per-replica top-K digests: sum hits and
    tokens_saved per fingerprint, keep the deepest depth seen (the
    same fp always names the same path, but replicas may disagree
    transiently during eviction churn), take the max last_tick (ticks
    are per-replica monotone — max is "most recently hot anywhere"),
    then re-rank."""
    by_fp = {}
    for digest in digests:
        for e in digest or ():
            cur = by_fp.get(e["fp"])
            if cur is None:
                by_fp[e["fp"]] = dict(e)
            else:
                cur["hits"] += e["hits"]
                cur["tokens_saved"] += e["tokens_saved"]
                cur["depth"] = max(cur["depth"], e["depth"])
                cur["last_tick"] = max(cur["last_tick"], e["last_tick"])
    return top_prefix_digest(by_fp.values(), k=k)
