"""Request-level flight recorder: one lifecycle trace per serving
request, flow-linked across engine step spans.

The engine-level observability (registry / host spans / watchdog)
answers "what is the ENGINE doing"; operating continuous batching
under heavy traffic is debugged per REQUEST — "why did request 4812
take 900 ms to first token?". This module is that Dapper-style answer:
every request gets a trace id and an append-only lifecycle record

    enqueued -> admitted(slot, bucket, group_size)
             -> prefill_dispatched -> first_token
             -> decode_window(tokens) ...
             -> retired(reason, slo_violations)

with perf_counter timestamps (monotone by construction — appended
under one lock from a monotonic clock).

Every event ALSO lands in the host-span recorder twice: a zero-length
marker span (``request/<event>`` with the rid and attrs) and a chrome
FLOW event (``ph:"s"/"t"/"f"``, one flow chain per request, id = rid).
Flow points bind to the slice enclosing their timestamp, so Perfetto
draws arrows from a request's enqueue marker through the engine step
spans it was admitted/prefilled/decoded in, to its retirement — load
``dump_chrome_trace()`` output and follow one request's life across
the steps.

Completed traces park in a bounded keep-last-N ring (the same leak
class PR 3 fixed for latency lists: a serve-forever process must not
accumulate per-request state). ``ServingEngine.request_trace(rid)``
reads one back; the ``/debug/requests`` endpoint serves them all.
"""
import collections
import threading
import time

from .tracing import default_recorder

# lifecycle event names (the validator test pins the order contract:
# enqueued <= admitted <= prefill_dispatched <= first_token <= retired)
ENQUEUED = "enqueued"
ADMITTED = "admitted"
ADMISSION_ROLLED_BACK = "admission_rolled_back"
PREFIX_HIT = "prefix_hit"
PREFILL_DISPATCHED = "prefill_dispatched"
PREFILL_CHUNK = "prefill_chunk"
DEPRIORITIZED = "deprioritized"
SHED = "shed"
DISPATCH_FAILED = "dispatch_failed"
REQUEUED = "requeued"
CALLBACK_ERROR = "callback_error"
DEADLINE_EXCEEDED = "deadline_exceeded"
FIRST_TOKEN = "first_token"
DECODE_WINDOW = "decode_window"
DRAFT_ACCEPTED = "draft_accepted"
DRAFT_REJECTED = "draft_rejected"
KV_EXPORTED = "kv_exported"
KV_IMPORTED = "kv_imported"
RETIRED = "retired"


class RequestTrace:
    """One request's lifecycle: an append-only list of
    ``{"event", "t", ...attrs}`` records (``t`` on the perf_counter
    clock) plus the retirement reason once retired."""

    __slots__ = ("rid", "events", "reason", "trace_id", "tenant_id")

    def __init__(self, rid):
        self.rid = int(rid)
        self.events = []
        self.reason = None
        # the request's distributed trace id (32-hex), stamped at
        # enqueue from the propagated TraceContext — the join key
        # between /debug/requests and the cross-replica trace surface
        self.trace_id = None
        # attribution: which tenant this request billed to (stamped at
        # enqueue; the ?tenant= filter on /debug/requests keys on it)
        self.tenant_id = None

    def t_of(self, event):
        """Timestamp of the FIRST occurrence of ``event``; None if it
        never happened (e.g. still queued)."""
        for e in self.events:
            if e["event"] == event:
                return e["t"]
        return None

    @property
    def retired(self):
        return self.reason is not None

    def as_dict(self):
        """JSON-safe view: absolute t plus ms-since-enqueue per event
        (the human-readable column when eyeballing /debug/requests)."""
        t0 = self.t_of(ENQUEUED)
        events = []
        for e in self.events:
            d = dict(e)
            d["t"] = round(d["t"], 6)
            if t0 is not None:
                d["t_rel_ms"] = round((e["t"] - t0) * 1000.0, 3)
            events.append(d)
        return {"rid": self.rid, "reason": self.reason,
                "trace_id": self.trace_id, "tenant_id": self.tenant_id,
                "events": events}


class FlightRecorder:
    """Thread-safe per-request lifecycle recorder.

    ``keep_last`` bounds the completed-trace ring; ``decode_window``
    sets the token-count granularity of mid-decode progress events
    (every N tokens one ``decode_window`` event records the cumulative
    count — cheap enough to leave on, detailed enough to see a slow
    decode tail). ``recorder`` is the HostSpanRecorder receiving the
    marker spans + flow events (default: the process-global one the
    chrome trace dump exports).
    """

    def __init__(self, recorder=None, keep_last=256, decode_window=32,
                 clock=time.perf_counter):
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        if decode_window < 1:
            raise ValueError("decode_window must be >= 1")
        self.keep_last = int(keep_last)
        self.decode_window = int(decode_window)
        self._recorder = recorder if recorder is not None \
            else default_recorder()
        self._clock = clock
        self._lock = threading.Lock()
        self._active = {}                       # rid -> RequestTrace
        self._done = collections.OrderedDict()  # rid -> RequestTrace
        self._dropped = 0

    # ------------------------------------------------------- recording
    def _event(self, rid, event, phase, attrs):
        t = self._clock()
        with self._lock:
            trace = self._active.get(rid)
            if trace is None:
                # first sighting of this rid — normally the enqueue,
                # but a recorder attached mid-flight starts a partial
                # trace rather than losing events. Either way the flow
                # chain must START here.
                trace = self._active[rid] = RequestTrace(rid)
                phase = "s"
            if "trace_id" in attrs and trace.trace_id is None:
                trace.trace_id = attrs["trace_id"]
            if "tenant" in attrs and trace.tenant_id is None:
                trace.tenant_id = attrs["tenant"]
            trace.events.append(dict({"event": event, "t": t}, **attrs))
        args = dict({"rid": rid}, **attrs)
        # marker span + flow point at the SAME timestamp: the flow
        # binds to the marker (or any enclosing engine span), linking
        # the request's life across step spans in Perfetto
        self._recorder.record(f"request/{event}", t, 0.0, args)
        self._recorder.record_flow(f"request {rid}", t, phase, rid,
                                   {"event": event})
        return t

    def enqueued(self, req):
        attrs = {"prompt_len": int(len(req.prompt)),
                 "max_new_tokens": int(req.max_new_tokens)}
        trace = getattr(req, "trace", None)
        if trace is not None:
            attrs["trace_id"] = trace.trace_id
        tenant = getattr(req, "tenant_id", None)
        if tenant is not None:
            attrs["tenant"] = tenant
        self._event(req.rid, ENQUEUED, "s", attrs)

    def admitted(self, req, slot, bucket, group_size):
        self._event(req.rid, ADMITTED, "t",
                    {"slot": int(slot), "bucket": int(bucket),
                     "group_size": int(group_size)})

    def admission_rolled_back(self, req):
        """The request's admission was undone before its prefill
        dispatched (dispatch-failure rollback): the preceding
        ``admitted`` event is void, the request is back at the front
        of the queue, and a later ``admitted`` is a fresh attempt —
        readers pairing admissions with retirements skip voided
        ones."""
        self._event(req.rid, ADMISSION_ROLLED_BACK, "t", {})

    def prefix_hit(self, req, cached_tokens, tail_tokens,
                   saved_ms=None):
        """The request's admission reused ``cached_tokens`` prompt
        tokens straight from the paged pool's radix prefix cache, so
        the prefill that follows dispatches only the ``tail_tokens``
        tail (emitted between ``admitted`` and ``prefill_dispatched``;
        absent = the prompt missed the cache entirely). ``saved_ms``
        is the cache observatory's estimated TTFT saving for this
        admission (cached tokens x measured per-token prefill cost;
        None until prefill measurements exist)."""
        attrs = {"cached_tokens": int(cached_tokens),
                 "tail_tokens": int(tail_tokens)}
        if saved_ms is not None:
            attrs["saved_ms"] = round(float(saved_ms), 3)
        self._event(req.rid, PREFIX_HIT, "t", attrs)

    def prefill_dispatched(self, req, bucket, group_size):
        self._event(req.rid, PREFILL_DISPATCHED, "t",
                    {"bucket": int(bucket),
                     "group_size": int(group_size)})

    def prefill_chunk(self, req, index, start, chunk_len, final):
        """One chunked-prefill dispatch for this request: chunk
        ``index`` covers prompt positions ``start..start+chunk_len``
        (``final`` marks the chunk whose logits emit the first token).
        The chunk chain is WHY a long prompt's trace shows decode
        windows of other requests progressing between its own prefill
        events — chunking is the co-scheduling made visible."""
        self._event(req.rid, PREFILL_CHUNK, "t",
                    {"chunk": int(index), "start": int(start),
                     "chunk_len": int(chunk_len),
                     "final": bool(final)})

    def deprioritized(self, req, headroom_ms):
        """The admission policy moved this queued request behind the
        still-SLO-viable queue (its own SLO is already lost);
        ``headroom_ms`` (<= 0) is the TTFT budget balance at decision
        time — the trace answers WHY it waited."""
        self._event(req.rid, DEPRIORITIZED, "t",
                    {"headroom_ms": round(float(headroom_ms), 3)})

    def shed(self, req, reason, headroom_ms):
        """The admission policy DROPPED this queued request (zero
        tokens served): a ``shed`` event with the reason + headroom at
        decision time, then the trace closes through the normal
        retirement path (reason "shed") so every trace still ends
        ``retired`` and the completed ring stays bounded."""
        self._event(req.rid, SHED, "t",
                    {"reason": str(reason),
                     "headroom_ms": round(float(headroom_ms), 3)})
        self.retired(req, "shed")

    def dispatch_failed(self, req, kind, error):
        """A dispatch carrying this request raised (and its admission
        rolled back): ``kind`` names the seam (prefill / chunk /
        decode), ``error`` the exception. A later ``admitted`` is the
        bounded-retry attempt; a ``retired(reason="error")`` means the
        retry budget ran out."""
        self._event(req.rid, DISPATCH_FAILED, "t",
                    {"kind": str(kind),
                     "error": f"{type(error).__name__}: {error}"[:200],
                     "failures": int(req.dispatch_failures)})

    def requeued(self, req, reason):
        """A supervisor restart re-queued this in-flight request for
        re-prefill of its prompt + already-emitted tokens; the earlier
        ``admitted``/``prefill_dispatched`` chain is void (like a
        rollback) and the replay re-runs it."""
        self._event(req.rid, REQUEUED, "t",
                    {"reason": str(reason),
                     "tokens_kept": int(len(req.generated))})

    def callback_error(self, req, error):
        """The user ``on_token`` callback raised; the engine caught it
        and kept streaming (the token WAS emitted and counted)."""
        self._event(req.rid, CALLBACK_ERROR, "t",
                    {"error": f"{type(error).__name__}: {error}"[:200]})

    def deadline_exceeded(self, req, overrun_ms):
        """The request blew its ``deadline_ms`` and is being retired
        (reason "deadline" follows); ``overrun_ms`` is how far past
        the deadline the engine noticed."""
        self._event(req.rid, DEADLINE_EXCEEDED, "t",
                    {"overrun_ms": round(float(overrun_ms), 3)})

    def token_emitted(self, req, n_tokens):
        """Account one emitted token: the FIRST is the TTFT lifecycle
        moment; thereafter every ``decode_window``-th token records a
        cumulative progress point."""
        n = int(n_tokens)
        if n == 1:
            self._event(req.rid, FIRST_TOKEN, "t", {})
        elif n % self.decode_window == 0:
            self._event(req.rid, DECODE_WINDOW, "t", {"tokens": n})

    def draft_accepted(self, req, accepted, drafted):
        """One verify dispatch kept ``accepted`` of this request's
        ``drafted`` speculative tokens (plus the bonus token the
        verify step always yields — counted by token_emitted)."""
        self._event(req.rid, DRAFT_ACCEPTED, "t",
                    {"accepted": int(accepted),
                     "drafted": int(drafted)})

    def draft_rejected(self, req, rejected, drafted):
        """One verify dispatch discarded ``rejected`` of this
        request's ``drafted`` speculative tokens (the tail after the
        first mismatch with the model's greedy choice)."""
        self._event(req.rid, DRAFT_REJECTED, "t",
                    {"rejected": int(rejected),
                     "drafted": int(drafted)})

    def kv_exported(self, req, blocks, wire_bytes):
        """The prefill tier serialized this request's KV blocks for a
        disaggregated handoff. Fires AFTER retirement (the slot was
        parked through it), so the event appends to the completed
        trace in the ring instead of reopening an active one."""
        t = self._clock()
        with self._lock:
            trace = self._done.get(req.rid) or self._active.get(req.rid)
            if trace is not None:
                trace.events.append(
                    {"event": KV_EXPORTED, "t": t,
                     "blocks": int(blocks),
                     "wire_bytes": int(wire_bytes)})
        self._recorder.record(
            f"request/{KV_EXPORTED}", t, 0.0,
            {"rid": req.rid, "blocks": int(blocks),
             "wire_bytes": int(wire_bytes)})

    def kv_imported(self, req, blocks, wire_bytes):
        """The decode tier bound this request's streamed KV blocks
        into its pool (the disaggregated admission moment)."""
        self._event(req.rid, KV_IMPORTED, "t",
                    {"blocks": int(blocks),
                     "wire_bytes": int(wire_bytes)})

    def retired(self, req, reason, **attrs):
        """Close the request's trace (reason: "eos" / "max_tokens" /
        anything the engine decides, e.g. future cancellations) and
        move it into the bounded completed ring."""
        base = {"reason": str(reason),
                "tokens": int(len(req.generated))}
        tenant = getattr(req, "tenant_id", None)
        if tenant is not None:
            # retirement carries the attribution too: a grep of
            # retired events alone can bill tokens per tenant
            base["tenant"] = tenant
        self._event(req.rid, RETIRED, "f", dict(base, **attrs))
        with self._lock:
            trace = self._active.pop(req.rid, None)
            if trace is None:
                return
            trace.reason = str(reason)
            self._done[req.rid] = trace
            while len(self._done) > self.keep_last:
                self._done.popitem(last=False)
                self._dropped += 1

    # -------------------------------------------------------- querying
    def trace(self, rid):
        """The RequestTrace for ``rid`` — completed or still active;
        None when unknown (never seen, or evicted from the ring)."""
        with self._lock:
            return self._done.get(rid) or self._active.get(rid)

    def completed(self):
        """Completed traces, oldest first (bounded at keep_last)."""
        with self._lock:
            return list(self._done.values())

    def active(self):
        with self._lock:
            return list(self._active.values())

    def state(self):
        with self._lock:
            return {
                "active": len(self._active),
                "completed_kept": len(self._done),
                "completed_dropped": self._dropped,
                "keep_last": self.keep_last,
                "decode_window": self.decode_window,
            }

    def debug_requests(self, tenant=None):
        """The ``/debug/requests`` JSON body: recorder state plus every
        kept trace, completed and in-flight. ``tenant`` filters both
        lists to one tenant's requests (the ``?tenant=<id>`` query
        form of the route); the ``state`` summary stays fleet-wide."""
        completed, active = self.completed(), self.active()
        if tenant:
            completed = [t for t in completed if t.tenant_id == tenant]
            active = [t for t in active if t.tenant_id == tenant]
        out = {
            "state": self.state(),
            "completed": [t.as_dict() for t in completed],
            "active": [t.as_dict() for t in active],
        }
        if tenant:
            out["tenant"] = tenant
        return out
