"""Tenant observatory: per-tenant attribution for the serving stack.

ROADMAP item #3's observability half. One :class:`TenantLedger` per
engine accrues, per tenant id: tokens in/out, goodput tokens, SLO
attainment/violations per dimension, queue-wait + TTFT reservoirs,
shed/timeout/abort counts, and cache-savings attribution — hooked at
the SAME ServingMetrics call sites as the global counters, so the
per-tenant sums equal the global counters exactly (the conservation
property the bench ``tenants`` scenario asserts bit-exactly).

Cardinality is bounded by construction: at most ``max_tenants`` live
tenant ids; any further unique id folds into ``"~other"`` with an
overflow counter — a 10k-unique-tenant flood costs O(max_tenants)
memory and one aggregate series, never a registry blowup (the generic
registry-level guard in ``observability.registry`` backstops every
other labelled family the same way).

The tenant id itself rides the PR-18 trace-context baggage end-to-end
(``POST /v1/generate`` body -> router admission baggage -> both
disaggregation hops -> KV handoff payload -> failover journal), so
attribution survives replica death and two-tier serving without any
wire-format change. Fleet-side, ``observability.fleet`` federates the
per-tenant series PR-11 style (counters sum, never mean-of-rates) and
judges fairness with the ``noisy_neighbor`` / ``tenant_starvation``
fleet detectors; ``tools/tenant_report.py`` renders the table.
"""
from .ledger import (  # noqa: F401
    DEFAULT_TENANT, OVERFLOW_TENANT, TENANT_ENTRY_KEYS, TENANT_KEYS,
    TenantLedger, disabled_tenant_report,
)

__all__ = [
    "DEFAULT_TENANT", "OVERFLOW_TENANT", "TENANT_ENTRY_KEYS",
    "TENANT_KEYS", "TenantLedger", "disabled_tenant_report",
]
