"""Decode-step roofline model: the analytic floor a decode dispatch
cannot beat, and the device tables to price it.

ROADMAP direction #2 ("Pallas paged decode attention kernel") starts
with "roofline first: extend tools/gpt_roofline.py with a decode-step
HBM model" — this module IS that model, shared between the engine's
perf attribution (snapshot()["perf"], /debug/perf), the roofline CLI
(tools/gpt_roofline.py --decode) and tests. A decode step is
memory-bound long before it is FLOP-bound: every step re-reads the
whole parameter set plus the K/V cache, so the HBM traffic term —
KV-read bytes per token as a function of batch, sequence length,
heads, and paged-vs-contiguous layout — is the yardstick any paged
attention kernel gets judged by.

Deliberately dependency-free (stdlib only): tools/perf_diff.py and
tools/gpt_roofline.py load this file directly via importlib without
importing the paddle_tpu package (no jax at tool startup), and the
engine imports it through paddle_tpu.observability.perf.

Layout model (why paged costs more under plain XLA):

  * **contiguous** (SlotKVPool): attention reads the pooled
    ``[slots, heads, cache_len, head_dim]`` K/V directly — one read of
    the full fixed-shape cache per step (the max_len over-read is the
    price of the zero-recompile fixed shape);
  * **paged_xla** (PagedKVPool behind a block table, composed in
    XLA): the gather MATERIALIZES a contiguous copy before attention
    reads it — pool read + copy write + attention read, ~3x the
    contiguous traffic. That factor is exactly what the Pallas kernel
    deletes by reading blocks in place, which is why the
    achieved-fraction gauge exists: the kernel becomes default only
    where measurements beat this model's floor;
  * **paged_pallas** (ops.paged_attention, PADDLE_PAGED_ATTN): the
    Pallas kernel streams blocks through VMEM straight from the pool
    — gather factor 1.0, and no max-len over-read: its index-map
    clamp stops the DMA at each slot's last LIVE block, so the read
    length is the live ``kv_len`` (callers may pass
    ``live_kv_len``), not the fixed cache capacity.

The boolean ``paged=`` argument is kept for callers predating the
three-way split (``paged=True`` means ``layout="paged_xla"``);
``layout=`` wins when both are given.
"""
import os

# reference chip when the real device is unknown (CPU smoke runs, new
# TPU generations before the tables learn them): v5e bf16 peak and HBM
# bandwidth — the same constants tools/gpt_roofline.py budgets with.
# Fractions computed against the reference are a machinery exercise,
# not an absolute claim; report()s flag device_peak=False for them.
REF_PEAK_FLOPS = 197e12
REF_HBM_BPS = 819e9

# published per-chip HBM bandwidth (bytes/sec) by PJRT device_kind
# prefix — the companion of the engine's _PEAK_FLOPS_BY_KIND table
_HBM_BPS_BY_KIND = (
    ("tpu v6", 1640e9),
    ("tpu v5p", 2765e9),
    ("tpu v5 lite", 819e9),
    ("tpu v5e", 819e9),
    ("tpu v4", 1228e9),
    ("tpu v3", 900e9),
    ("tpu v2", 700e9),
)

# XLA-composed paged attention: gather reads the pool, writes a
# contiguous copy, attention reads the copy back (vs one direct read
# on the contiguous layout)
PAGED_GATHER_FACTOR = 3.0

# the decode K/V layouts the model prices; per-layout gather
# materialization factor on the KV-read term
LAYOUTS = ("contiguous", "paged_xla", "paged_pallas")
_GATHER_FACTORS = {
    "contiguous": 1.0,
    "paged_xla": PAGED_GATHER_FACTOR,
    "paged_pallas": 1.0,
}


def resolve_layout(paged=False, layout=None):
    """Back-compat shim: the pre-kernel API was ``paged: bool``."""
    if layout is None:
        return "paged_xla" if paged else "contiguous"
    if layout not in _GATHER_FACTORS:
        raise ValueError(f"unknown KV layout {layout!r}; "
                         f"expected one of {LAYOUTS}")
    return layout


def hbm_bps_for(device_kind):
    """HBM bandwidth (bytes/sec) for a PJRT device_kind; the
    PADDLE_TPU_HBM_BPS env var covers unknown kinds; None when
    nothing is known (callers fall back to REF_HBM_BPS and flag it)."""
    kind = str(device_kind).lower()
    for prefix, bw in _HBM_BPS_BY_KIND:
        if kind.startswith(prefix):
            return bw
    env = os.environ.get("PADDLE_TPU_HBM_BPS")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    return None


def roofline_floor(flops, bytes_accessed, peak_flops, hbm_bps):
    """(floor_seconds, bound) — the time one dispatch cannot beat:
    max of the compute term and the memory term, with ``bound`` naming
    the binding resource ("flops" | "hbm"). Terms whose inputs are
    missing/zero drop out; (None, None) when nothing is computable."""
    t_flops = None
    if flops and peak_flops:
        t_flops = float(flops) / float(peak_flops)
    t_hbm = None
    if bytes_accessed and hbm_bps:
        t_hbm = float(bytes_accessed) / float(hbm_bps)
    if t_flops is None and t_hbm is None:
        return None, None
    if t_hbm is None or (t_flops is not None and t_flops >= t_hbm):
        return t_flops, "flops"
    return t_hbm, "hbm"


def kv_read_bytes_per_token(kv_len, num_layers, num_heads, head_dim,
                            kv_bytes=2, paged=False, layout=None):
    """HBM bytes attention reads to serve ONE decode token: K and V
    across every layer over ``kv_len`` positions, times the gather
    materialization factor on the XLA-composed paged layout (the
    Pallas in-place layout pays factor 1.0)."""
    base = 2.0 * num_layers * num_heads * head_dim * kv_len * kv_bytes
    return base * _GATHER_FACTORS[resolve_layout(paged, layout)]


def decode_step_model(batch, kv_len, num_layers, num_heads, head_dim,
                      n_params, param_bytes=2, kv_bytes=2, paged=False,
                      layout=None, live_kv_len=None,
                      peak_flops=None, hbm_bps=None):
    """Analytic cost of ONE pooled decode dispatch (``batch`` slots,
    one token each, attending over ``kv_len`` cached positions — the
    engine passes its fixed cache_len, since the fixed-shape program
    reads the whole pooled cache regardless of live lengths).

    On the ``paged_pallas`` layout the kernel stops reading at each
    slot's live length, so the KV-read term uses ``live_kv_len`` when
    given (the other layouts always read the fixed ``kv_len`` — the
    over-read is part of their price).

    Returns a JSON-safe dict: the traffic decomposition (KV read per
    token and total, KV append write, parameter read), matmul +
    attention FLOPs, arithmetic intensity, and — when peak_flops /
    hbm_bps are given — the roofline floor and its binding resource.
    """
    layout = resolve_layout(paged, layout)
    hidden = num_heads * head_dim
    kv_len_read = kv_len
    if layout == "paged_pallas" and live_kv_len is not None:
        kv_len_read = min(int(live_kv_len), int(kv_len))
    kv_tok = kv_read_bytes_per_token(kv_len_read, num_layers,
                                     num_heads, head_dim,
                                     kv_bytes=kv_bytes, layout=layout)
    kv_read = batch * kv_tok
    # one position appended per layer, K and V
    kv_write = batch * 2.0 * num_layers * num_heads * head_dim * kv_bytes
    param_read = float(n_params) * param_bytes
    bytes_total = kv_read + kv_write + param_read
    # dense matmuls touch every parameter twice per token; attention
    # is QK^T + AV, 2 * kv_len * hidden multiply-adds each, per layer
    flops = batch * (2.0 * n_params
                     + 4.0 * kv_len * hidden * num_layers)
    floor_s, bound = roofline_floor(flops, bytes_total, peak_flops,
                                    hbm_bps)
    return {
        "batch": int(batch),
        "kv_len": int(kv_len),
        "num_layers": int(num_layers),
        "num_heads": int(num_heads),
        "head_dim": int(head_dim),
        "n_params": int(n_params),
        # "paged" keeps the pre-kernel bool meaning (is the POOL
        # paged); "layout" names the attention path actually priced
        "paged": layout != "contiguous",
        "layout": layout,
        "gather_factor": _GATHER_FACTORS[layout],
        "kv_len_read": int(kv_len_read),
        "kv_read_bytes_per_token": kv_tok,
        "kv_read_bytes": kv_read,
        "kv_write_bytes": kv_write,
        "param_read_bytes": param_read,
        "bytes_total": bytes_total,
        "flops": flops,
        "arithmetic_intensity": flops / bytes_total
        if bytes_total else None,
        "peak_flops": peak_flops,
        "hbm_bps": hbm_bps,
        "floor_s": floor_s,
        "floor_ms": round(floor_s * 1e3, 6)
        if floor_s is not None else None,
        "bound": bound,
    }
