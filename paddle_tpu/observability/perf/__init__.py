"""Performance observatory: per-program device-time attribution,
roofline-anchored efficiency, and the cross-run perf ledger.

PRs 3/4/8 made a single run richly observable; this package makes
performance COMPARABLE — within a step ("which compiled program did
the wall go to, and how close to the hardware floor does it run") and
across runs ("is that faster or slower than last time"):

  * **attribution.ProgramPerf** — every AOT executable dispatch
    (prefill buckets, chunk program, pooled decode, per pool flavor)
    records measured dispatch/sync wall seconds against its AOT-table
    key into registry histograms; ``snapshot()["perf"]`` and
    ``/debug/perf`` decompose a step into named programs;
  * **roofline** — the analytic decode-step HBM/FLOPs model (KV-read
    bytes per token by batch/seq/heads/layout, paged gather factor)
    plus device peak/HBM tables; joined with ``executable_cost`` it
    yields the ``serving_roofline_fraction{program}`` gauge — the
    go/no-go yardstick for ROADMAP direction #2's Pallas kernel;
  * **ledger** — the schema-versioned cross-run JSONL perf ledger
    (``bench_artifacts/perf_ledger.jsonl``) and the robust
    median+MAD comparison ``tools/perf_diff.py`` gates CI with.

roofline.py and ledger.py are deliberately stdlib-only so the CLI
tools load them via importlib without importing paddle_tpu (no jax at
tool startup).
"""
from .attribution import (  # noqa: F401
    PERF_KEYS, PERF_PROGRAM_KEYS, PERF_SPEC_KEYS, ProgramPerf,
    build_decode_model, disabled_perf_report, disabled_spec_report,
    format_program_key,
)
from .ledger import (  # noqa: F401
    LEDGER_ROW_KEYS, MEASUREMENTS, PERF_LEDGER_SCHEMA, append_rows,
    compact, compare, config_digest, make_row, prune, read_rows,
)
from .roofline import (  # noqa: F401
    PAGED_GATHER_FACTOR, REF_HBM_BPS, REF_PEAK_FLOPS,
    decode_step_model, hbm_bps_for, kv_read_bytes_per_token,
    roofline_floor,
)
