"""Per-program device-time attribution: which compiled executable the
step wall actually went to, and how close each one runs to its
roofline.

The engine's span counters answer "how long did serving/step take";
this module answers the next question — WHICH program: every AOT
dispatch (bucketed/grouped prefill, the per-flavor chunk program, the
pooled decode) and every harvest sync records its measured wall
seconds against its AOT-table key, accumulated into per-program
registry histograms::

    serving_program_dispatch_seconds{program="decode"}
    serving_program_sync_seconds{program="prefill/b16/g4"}
    serving_roofline_fraction{program="decode"}

The roofline fraction joins three facts the stack already collects:
the measured per-dispatch wall (here), the program's
``cost_analysis`` flops/bytes (watchdog.executable_cost, bound via
``bind_cost`` at compile time), and the device's peak FLOP/s + HBM
bandwidth (set once via ``set_device``; unknown devices fall back to
the v5e reference constants with ``device_peak: false``). fraction =
roofline floor / measured per-dispatch wall — the go/no-go yardstick
ROADMAP direction #2 judges the Pallas paged-attention kernel by.

``report()`` is the ``snapshot()["perf"]`` / ``/debug/perf`` body;
its key set is pinned by tests/test_observability.py. Hot-path cost
is two perf_counter reads plus one histogram observe per dispatch and
per sync (~1-2us/step) — probe-measured in the bench artifact's
``perf.overhead`` section, same discipline as the PR-8 health tick.
"""
import threading

from .roofline import (REF_HBM_BPS, REF_PEAK_FLOPS, decode_step_model,
                       roofline_floor)

__all__ = ["ProgramPerf", "disabled_perf_report",
           "disabled_spec_report", "format_program_key", "PERF_KEYS",
           "PERF_PROGRAM_KEYS", "PERF_SPEC_KEYS"]

# snapshot()["perf"] schema contract (additions only, never renames)
PERF_KEYS = (
    "enabled", "device", "programs", "attributed_s", "step_total_s",
    "attributed_fraction", "decode_roofline", "spec",
)
# the "spec" sub-section (speculative-decoding economy; the serving
# metrics facade fills it from its counters, this module only pins the
# disabled shape so the schema contract holds on bare reports)
PERF_SPEC_KEYS = (
    "enabled", "k", "drafted_tokens", "accepted_tokens",
    "rejected_tokens", "emitted_tokens", "verify_steps", "slot_steps",
    "fallback_steps", "acceptance_rate",
    "effective_tokens_per_dispatch",
)
# per-program entry schema inside "programs"
PERF_PROGRAM_KEYS = (
    "dispatches", "dispatch_s", "syncs", "sync_s", "total_s",
    "avg_ms", "cost", "roofline_floor_ms", "roofline_fraction",
    "bound",
)


def format_program_key(key):
    """Stable human-readable label for an engine AOT-table key:
    ("decode",) -> "decode", ("prefill", 16, 4) -> "prefill/b16/g4",
    ("paged_prefill", 32) -> "paged_prefill/b32",
    ("chunk_prefill", 8) -> "chunk_prefill/c8"."""
    if isinstance(key, str):
        return key
    kind, rest = key[0], key[1:]
    if kind == "prefill" and len(rest) == 2:
        return f"prefill/b{rest[0]}/g{rest[1]}"
    if kind == "paged_prefill" and len(rest) == 1:
        return f"paged_prefill/b{rest[0]}"
    if kind == "chunk_prefill" and len(rest) == 1:
        return f"chunk_prefill/c{rest[0]}"
    return "/".join(str(p) for p in key)


def disabled_spec_report():
    """The ``perf["spec"]`` section when speculative decoding is off
    (or the report is produced outside a serving engine) — same key
    set as the live section the serving metrics facade fills."""
    return {"enabled": False, "k": None, "drafted_tokens": 0,
            "accepted_tokens": 0, "rejected_tokens": 0,
            "emitted_tokens": 0, "verify_steps": 0, "slot_steps": 0,
            "fallback_steps": 0, "acceptance_rate": None,
            "effective_tokens_per_dispatch": None}


def disabled_perf_report():
    """The ``snapshot()["perf"]`` section of an engine built with
    perf=False — same key set as a live report, so the snapshot
    schema contract holds either way."""
    return {"enabled": False, "device": None, "programs": {},
            "attributed_s": 0.0, "step_total_s": None,
            "attributed_fraction": None, "decode_roofline": None,
            "spec": disabled_spec_report()}


class _Program:
    """One program's measured-time accumulators (histogram children
    read directly — count/sum ARE the dispatch count and total wall)
    plus its compile-time cost annotation."""

    __slots__ = ("h_dispatch", "h_sync", "g_frac", "cost")

    def __init__(self, h_dispatch, h_sync, g_frac):
        self.h_dispatch = h_dispatch
        self.h_sync = h_sync
        self.g_frac = g_frac
        self.cost = None

    def measured_avg_s(self):
        """Host-observed seconds per dispatch: (dispatch + sync wall)
        over dispatch count. Pipelining overlaps a step's sync with
        the next step's dispatch, so this is the engine's EFFECTIVE
        per-dispatch cost — conservative vs pure device time, which
        makes the roofline fraction an honest lower bound."""
        n = self.h_dispatch.count
        if not n:
            return None
        return (self.h_dispatch.sum + self.h_sync.sum) / n


class ProgramPerf:
    """Registry-backed per-program perf accumulator. ``enabled=False``
    registers nothing and turns every record into a no-op (the engine
    additionally skips the perf_counter reads), so a perf-off engine
    pays zero and exposes the disabled report shape."""

    def __init__(self, registry, enabled=True):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._programs = {}      # AOT key tuple -> _Program
        self._device = None
        self._peak_flops = REF_PEAK_FLOPS
        self._hbm_bps = REF_HBM_BPS
        self._decode_model = None
        if not self.enabled:
            return
        self._h_dispatch = registry.histogram(
            "serving_program_dispatch_seconds",
            "measured wall seconds issuing ONE dispatch of each "
            "compiled program (AOT-table key as the program label)",
            labelnames=("program",))
        self._h_sync = registry.histogram(
            "serving_program_sync_seconds",
            "measured wall seconds blocked reading back each "
            "program's dispatched results",
            labelnames=("program",))
        self._g_frac = registry.gauge(
            "serving_roofline_fraction",
            "achieved fraction of the device roofline per program: "
            "cost_analysis floor over measured per-dispatch wall "
            "(0 until the program has cost + measurements)",
            labelnames=("program",))

    # ------------------------------------------------------- device
    def set_device(self, platform, kind, peak_flops=None,
                   hbm_bps=None):
        """Price the roofline: the device's peak FLOP/s and HBM
        bytes/sec. Unknown values fall back to the v5e reference
        constants — the report carries ``device_peak`` / ``device_hbm``
        flags so a reference-priced fraction is never mistaken for a
        real-device one."""
        self._peak_flops = float(peak_flops) if peak_flops \
            else REF_PEAK_FLOPS
        self._hbm_bps = float(hbm_bps) if hbm_bps else REF_HBM_BPS
        self._device = {
            "platform": str(platform),
            "kind": str(kind),
            "peak_flops": self._peak_flops,
            "hbm_bps": self._hbm_bps,
            "device_peak": bool(peak_flops),
            "device_hbm": bool(hbm_bps),
        }

    @property
    def peak_flops(self):
        return self._peak_flops

    @property
    def hbm_bps(self):
        return self._hbm_bps

    def set_decode_model(self, model):
        """Attach the analytic decode-step model (roofline.
        decode_step_model output) the report joins against the decode
        program's measurements."""
        self._decode_model = dict(model)

    # ---------------------------------------------------- recording
    def _prog(self, key):
        p = self._programs.get(key)
        if p is None:
            with self._lock:
                p = self._programs.get(key)
                if p is None:
                    label = format_program_key(key)
                    p = _Program(self._h_dispatch.labels(label),
                                 self._h_sync.labels(label),
                                 self._g_frac.labels(label))
                    self._programs[key] = p
        return p

    def prefill_seconds(self):
        """Measured wall seconds accrued by the prefill-family
        programs (bucketed/grouped, paged, chunked) — dispatch + sync.
        The cache observatory divides this by prefill-computed tokens
        for its per-token savings attribution."""
        if not self.enabled:
            return 0.0
        with self._lock:
            items = list(self._programs.items())
        total = 0.0
        for key, prog in items:
            kind = key if isinstance(key, str) else key[0]
            if kind in ("prefill", "paged_prefill", "chunk_prefill"):
                total += prog.h_dispatch.sum + prog.h_sync.sum
        return total

    def record_dispatch(self, key, dt):
        if not self.enabled:
            return
        self._prog(key).h_dispatch.observe(dt)

    def record_sync(self, key, dt):
        if not self.enabled:
            return
        self._prog(key).h_sync.observe(dt)

    def bind_cost(self, key, cost):
        """Attach a program's compile-time cost_analysis (the engine
        calls this from _compiled, same place the watchdog event is
        annotated) and arm its pull-gauge: the Prometheus fraction is
        computed from live accumulators at scrape time."""
        if not self.enabled or not cost:
            return
        prog = self._prog(key)
        prog.cost = dict(cost)

        def frac(prog=prog, self=self):
            f = self._fraction(prog)
            return 0.0 if f is None else f
        prog.g_frac.set_function(frac)

    # ---------------------------------------------------- reporting
    def _floor_s(self, prog):
        cost = prog.cost
        if not cost:
            return None, None
        return roofline_floor(cost.get("flops"),
                              cost.get("bytes_accessed"),
                              self._peak_flops, self._hbm_bps)

    def _fraction(self, prog):
        floor_s, _ = self._floor_s(prog)
        measured = prog.measured_avg_s()
        if floor_s is None or not measured:
            return None
        return floor_s / measured

    def report(self, step_total_s=None):
        """The ``snapshot()["perf"]`` / ``/debug/perf`` body. Pass the
        accrued ``serving/step`` span seconds as ``step_total_s`` so
        the report carries how much of the step wall the per-program
        attribution accounts for."""
        if not self.enabled:
            return disabled_perf_report()
        with self._lock:
            items = sorted(self._programs.items(),
                           key=lambda kv: format_program_key(kv[0]))
        programs = {}
        attributed = 0.0
        decode_measured = None
        for key, prog in items:
            d_n, d_s = prog.h_dispatch.count, prog.h_dispatch.sum
            s_n, s_s = prog.h_sync.count, prog.h_sync.sum
            if not d_n and not s_n:
                continue
            total = d_s + s_s
            attributed += total
            avg_ms = total / d_n * 1e3 if d_n else None
            floor_s, bound = self._floor_s(prog)
            frac = self._fraction(prog)
            label = format_program_key(key)
            if key == ("decode",):
                decode_measured = avg_ms
            programs[label] = {
                "dispatches": d_n,
                "dispatch_s": round(d_s, 6),
                "syncs": s_n,
                "sync_s": round(s_s, 6),
                "total_s": round(total, 6),
                "avg_ms": round(avg_ms, 4) if avg_ms is not None
                else None,
                "cost": dict(prog.cost) if prog.cost else None,
                "roofline_floor_ms": round(floor_s * 1e3, 6)
                if floor_s is not None else None,
                "roofline_fraction": round(frac, 6)
                if frac is not None else None,
                "bound": bound,
            }
        decode_roofline = None
        if self._decode_model is not None:
            model = dict(self._decode_model)
            floor_ms = model.get("floor_ms")
            decode_roofline = {
                "model": model,
                "measured_avg_ms": decode_measured,
                "achieved_fraction": round(floor_ms / decode_measured,
                                           6)
                if floor_ms and decode_measured else None,
            }
        return {
            "enabled": True,
            "device": dict(self._device) if self._device else None,
            "programs": programs,
            "attributed_s": round(attributed, 6),
            "step_total_s": round(step_total_s, 6)
            if step_total_s is not None else None,
            "attributed_fraction": round(attributed / step_total_s, 4)
            if step_total_s else None,
            "decode_roofline": decode_roofline,
            # overwritten by the serving metrics facade with the live
            # speculation economy; the key exists on every report
            "spec": disabled_spec_report(),
        }


def build_decode_model(batch, kv_len, num_layers, num_heads, head_dim,
                       n_params, param_bytes, kv_bytes, paged,
                       peak_flops, hbm_bps, layout=None):
    """Thin convenience wrapper the engine uses (keeps its import
    surface to this package). ``layout`` names the attention path the
    engine actually resolved ("contiguous" | "paged_xla" |
    "paged_pallas") so serving_roofline_fraction prices the path that
    is running; the bool ``paged`` alone means the XLA gather."""
    return decode_step_model(
        batch=batch, kv_len=kv_len, num_layers=num_layers,
        num_heads=num_heads, head_dim=head_dim, n_params=n_params,
        param_bytes=param_bytes, kv_bytes=kv_bytes, paged=paged,
        layout=layout, peak_flops=peak_flops, hbm_bps=hbm_bps)
