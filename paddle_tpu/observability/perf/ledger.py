"""Cross-run perf ledger: one normalized JSONL row per
(scenario, metric) per bench run, plus the robust comparison logic
``tools/perf_diff.py`` gates CI with.

``bench_artifacts/`` holds a dozen serving artifacts no tool compares;
this ledger is the durable, append-only record that makes performance
a TRAJECTORY: every ``bench_serving.py`` run appends rows like::

    {"schema": "paddle_tpu.perf_ledger/v1", "timestamp": "...",
     "run_id": "serving_smoke_...json", "source": "live-smoke",
     "scenario": "overload", "metric": "goodput_improvement",
     "value": 4.2, "unit": "ratio", "direction": "higher_better",
     "config_digest": "1a2b3c4d5e6f", "device": "cpu",
     "rel_threshold": 0.35}

Rows are self-describing on purpose: ``direction`` says which way is
worse, ``config_digest`` isolates incomparable configurations (a
changed workload starts a fresh baseline instead of a false alarm),
and the optional per-row ``rel_threshold`` lets the WRITER declare a
metric's noise floor (raw CPU timings get a looser gate than ratios).
Timestamps are passed in by the caller — this module never reads a
clock, so replays and tests are deterministic.

``compare()`` implements the regression verdict: current (last) row
per group vs the median of its history, flagged only when the
relative worsening exceeds the threshold AND clears a MAD-based noise
gate over that history (a single noisy baseline row can't shadow-ban
a metric, a genuinely bimodal history widens its own gate).

Retention and triage are separate knobs: ``compact()`` bounds healthy
history (newest N rows per series), ``prune()`` retires poisoned
history — a host-overloaded run whose trailing rows keep the gate
red, or a renamed metric's stale series (tools/perf_diff.py
--prune-run / --prune-series, so triage is recorded CLI usage, not a
hand edit).

Deliberately dependency-free (stdlib only): tools/perf_diff.py loads
this file directly via importlib, so the CI gate starts in
milliseconds without importing paddle_tpu (or jax).
"""
import hashlib
import json
import math

PERF_LEDGER_SCHEMA = "paddle_tpu.perf_ledger/v1"

# required row fields (rel_threshold is optional, writer-declared)
LEDGER_ROW_KEYS = (
    "schema", "timestamp", "run_id", "source", "scenario", "metric",
    "value", "unit", "direction", "config_digest", "device",
)

_DIRECTIONS = ("higher_better", "lower_better")

# optional writer-declared row provenance: "timed" rows ride wall
# clocks (noisy on a shared smoke runner), "deterministic" rows are
# measured from live run counters but fully determined by the seeded
# workload + code (zero variance across healthy runs — any movement
# IS a code-path change, so they carry tight thresholds)
MEASUREMENTS = ("timed", "deterministic")


def config_digest(config):
    """Short stable digest of a (JSON-serializable) config dict: rows
    from different workload configurations never compare against each
    other — a config change establishes a fresh baseline."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode("utf-8")).hexdigest()[:12]


def make_row(*, timestamp, run_id, source, scenario, metric, value,
             unit, direction, config_digest, device,
             rel_threshold=None, measurement=None):
    """Validated ledger row. ``timestamp`` is caller-provided (no
    clock reads here); ``direction`` must name which way is worse;
    ``value`` must be a finite number; ``measurement`` optionally
    declares the row's provenance (see ``MEASUREMENTS``)."""
    if direction not in _DIRECTIONS:
        raise ValueError(f"direction must be one of {_DIRECTIONS}, "
                         f"got {direction!r}")
    v = float(value)
    if not math.isfinite(v):
        raise ValueError(f"value must be finite, got {value!r}")
    if not scenario or not metric:
        raise ValueError("scenario and metric must be non-empty")
    row = {
        "schema": PERF_LEDGER_SCHEMA,
        "timestamp": str(timestamp),
        "run_id": str(run_id),
        "source": str(source),
        "scenario": str(scenario),
        "metric": str(metric),
        "value": v,
        "unit": str(unit),
        "direction": direction,
        "config_digest": str(config_digest),
        "device": str(device),
    }
    if rel_threshold is not None:
        t = float(rel_threshold)
        if not (0.0 < t < 10.0):
            raise ValueError(f"rel_threshold out of range: {t}")
        row["rel_threshold"] = t
    if measurement is not None:
        if measurement not in MEASUREMENTS:
            raise ValueError(f"measurement must be one of "
                             f"{MEASUREMENTS}, got {measurement!r}")
        row["measurement"] = measurement
    return row


def append_rows(path, rows):
    """Append validated rows to the JSONL ledger (one object per
    line). Rows missing required keys are rejected before anything is
    written — a partial append never corrupts the ledger."""
    rows = list(rows)
    for row in rows:
        missing = [k for k in LEDGER_ROW_KEYS if k not in row]
        if missing:
            raise ValueError(f"ledger row missing {missing}: {row}")
    with open(path, "a") as fh:
        for row in rows:
            fh.write(json.dumps(row, sort_keys=True) + "\n")
    return len(rows)


def read_rows(path):
    """(rows, skipped): every parseable row carrying the ledger
    schema, in file (= append) order; junk lines and foreign schemas
    are counted, never fatal — one corrupt line must not kill the CI
    gate."""
    rows, skipped = [], 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                skipped += 1
                continue
            if not isinstance(row, dict) \
                    or row.get("schema") != PERF_LEDGER_SCHEMA \
                    or not isinstance(row.get("value"), (int, float)):
                skipped += 1
                continue
            rows.append(row)
    return rows, skipped


def compact(path, keep_last):
    """Bound the ledger: rewrite it keeping only the NEWEST
    ``keep_last`` rows per (scenario, metric, config_digest) series,
    preserving append order. The ledger grows one row per (scenario,
    metric) per bench run forever — compaction is the retention knob
    (``bench_serving.py --ledger-keep N`` / $BENCH_LEDGER_KEEP,
    default off). The rewrite is atomic (temp file + replace), so a
    crash mid-compaction never corrupts the ledger; junk lines and
    foreign schemas are dropped (they were already invisible to
    ``compare()``). Returns ``(kept, dropped)`` row counts."""
    keep_last = int(keep_last)
    if keep_last < 1:
        raise ValueError(f"keep_last must be >= 1, got {keep_last}")
    import os
    rows, skipped = read_rows(path)
    per_series = {}
    for row in rows:
        key = (row["scenario"], row["metric"],
               row.get("config_digest", ""))
        per_series.setdefault(key, []).append(row)
    keep = set()
    for series in per_series.values():
        for row in series[-keep_last:]:
            keep.add(id(row))
    kept = [r for r in rows if id(r) in keep]
    tmp = path + ".compact.tmp"
    with open(tmp, "w") as fh:
        for row in kept:
            fh.write(json.dumps(row, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return len(kept), len(rows) - len(kept) + skipped


def prune(path, run_ids=(), series=()):
    """Triage the ledger: atomically rewrite it DROPPING every row
    whose ``run_id`` is in ``run_ids``, or whose (scenario, metric)
    matches a ``"scenario/metric"`` spec in ``series``.

    ``compact`` bounds healthy history; ``prune`` retires poisoned
    history — a host-overloaded run that left red verdicts behind
    (``compare()`` judges each series' LAST row, so one bad trailing
    run keeps the gate red until a newer run lands or the bad rows
    are pruned), or a retired metric name whose stale series would
    otherwise shadow the trajectory table forever. Exposed as
    ``tools/perf_diff.py --prune-run / --prune-series`` so triage is
    a recorded CLI operation, not a hand edit. Junk lines and foreign
    schemas are dropped like ``compact`` does (they were already
    invisible to ``compare()``); the rewrite is atomic (temp file +
    replace). Returns ``(kept, dropped)`` row counts."""
    import os
    run_ids = {str(r) for r in run_ids}
    pairs = set()
    for spec in series:
        scenario, sep, metric = str(spec).partition("/")
        if not sep or not scenario or not metric:
            raise ValueError(f"series spec must be "
                             f"'scenario/metric', got {spec!r}")
        pairs.add((scenario, metric))
    rows, skipped = read_rows(path)
    kept = [r for r in rows
            if r.get("run_id") not in run_ids
            and (r.get("scenario"), r.get("metric")) not in pairs]
    tmp = path + ".prune.tmp"
    with open(tmp, "w") as fh:
        for row in kept:
            fh.write(json.dumps(row, sort_keys=True) + "\n")
    os.replace(tmp, path)
    return len(kept), len(rows) - len(kept) + skipped


def _median(xs):
    s = sorted(xs)
    n = len(s)
    if not n:
        return None
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


def _mad(xs, center):
    """Median absolute deviation around ``center``."""
    if not xs:
        return 0.0
    return _median([abs(x - center) for x in xs]) or 0.0


def compare(rows, default_rel_threshold=0.35, mad_k=3.0):
    """Judge the LAST row of every (scenario, metric, config_digest)
    group against the median of its earlier rows.

    Verdicts: ``baseline`` (no history — first run establishes it),
    ``ok``, ``improvement`` (better than baseline by more than the
    threshold), ``regression``. A regression requires BOTH gates:

      * relative: worse than baseline by > rel_threshold (the row's
        own ``rel_threshold`` when present, else the default);
      * noise: |current - baseline| > mad_k * 1.4826 * MAD(history)
        (vacuous when history is too short to estimate spread — the
        relative gate alone decides then).

    Returns a list of group results sorted by (scenario, metric),
    each carrying the trajectory (history values + current) so
    callers can print it."""
    groups = {}
    for row in rows:
        key = (row["scenario"], row["metric"],
               row.get("config_digest", ""))
        groups.setdefault(key, []).append(row)
    results = []
    for (scenario, metric, digest) in sorted(groups):
        grp = groups[(scenario, metric, digest)]
        cur = grp[-1]
        history = [float(r["value"]) for r in grp[:-1]]
        value = float(cur["value"])
        direction = cur.get("direction", "higher_better")
        threshold = float(cur.get("rel_threshold",
                                  default_rel_threshold))
        result = {
            "scenario": scenario,
            "metric": metric,
            "config_digest": digest,
            "unit": cur.get("unit", ""),
            "direction": direction,
            "runs": len(grp),
            "history": history,
            "current": value,
            "current_run": cur.get("run_id"),
            "threshold": threshold,
            "baseline": None,
            "worse_by": None,
            "verdict": "baseline",
        }
        if history:
            baseline = _median(history)
            result["baseline"] = baseline
            if baseline:
                delta = (value - baseline) / abs(baseline)
                worse_by = -delta if direction == "higher_better" \
                    else delta
                result["worse_by"] = round(worse_by, 4)
                noise = mad_k * 1.4826 * _mad(history, baseline)
                beyond_noise = abs(value - baseline) > noise
                if worse_by > threshold and beyond_noise:
                    result["verdict"] = "regression"
                elif worse_by < -threshold:
                    result["verdict"] = "improvement"
                else:
                    result["verdict"] = "ok"
            else:
                # a zero baseline carries no scale: judge on absolute
                # worsening direction only, never divide
                worse = (value < 0) if direction == "higher_better" \
                    else (value > 0)
                result["worse_by"] = None
                result["verdict"] = "regression" if worse else "ok"
        results.append(result)
    return results
