"""Compile watchdog: attributed compile accounting + a steady-state
recompile alarm.

The serving engine's zero-recompile steady state (ROADMAP, PR 1/2) is
an AOT-table construction property — but in production the thing you
need when it BREAKS is attribution: which call-site compiled, with
what abstract-shape signature, and was the system supposed to be warm.
The watchdog records every compile event (key, signature, call-site,
warm/cold) and, once ``declare_warmup_complete()`` is called, flags —
or raises, in ``mode="raise"`` — any further compile, carrying the
full attribution in the report/exception instead of a bare counter
drift.

Two integration points:

  * the engine's AOT table (ServingEngine._compiled) records every
    executable build directly — ``metrics.compiles`` stays the exact
    counter, the watchdog makes it attributable and testable;
  * ``watch_jax_lowering(watchdog)`` patches the generic
    ``jax.stages.Lowered.compile`` AOT entry point for the duration of
    a ``with`` block, so any lowering-based compile in scope (training
    AOT paths, third-party code) is captured without its cooperation.

Compile records also carry DEVICE COST telemetry: the engine attaches
``compiled.cost_analysis()`` (flops, bytes accessed — via
``executable_cost()``) and ``device.memory_stats()`` (HBM in-use /
limit — via ``device_memory_stats()``) to each event with
``annotate()``. Both helpers are best-effort: backends that don't
report (CPU has no memory_stats; some runtimes hide cost_analysis)
yield None, never an exception — the graceful-fallback contract the
serving engine and bench artifacts rely on.
"""
import contextlib
import hashlib
import os
import threading
import traceback

_SELF = os.path.basename(__file__)


class CompileAfterWarmupError(RuntimeError):
    """A compile happened after warmup was declared complete — the
    zero-recompile invariant broke. The message carries the full
    attribution (key, abstract-shape signature, call-site)."""


def abstract_signature(args, max_leaves_shown=6):
    """Stable abstract-shape signature of a pytree of arrays: a short
    human-readable prefix (first few leaves as dtype[shape]) plus a
    digest over ALL leaves — two argument sets get the same signature
    iff every leaf matches in dtype and shape."""
    try:
        import jax
        leaves = jax.tree_util.tree_leaves(args)
    except Exception:  # pragma: no cover - jax always present here
        leaves = list(args) if isinstance(args, (list, tuple)) else [args]
    parts = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None:
            parts.append(type(leaf).__name__)
        else:
            dims = ",".join(str(d) for d in shape)
            parts.append(f"{dtype}[{dims}]")
    digest = hashlib.sha1("|".join(parts).encode()).hexdigest()[:12]
    shown = ";".join(parts[:max_leaves_shown])
    more = len(parts) - max_leaves_shown
    if more > 0:
        shown += f";+{more} leaves"
    return f"{shown}#{digest}"


def executable_cost(compiled):
    """Best-effort device cost model of one compiled executable:
    ``{"flops": ..., "bytes_accessed": ...}`` (floats, per execution)
    from ``compiled.cost_analysis()``; None when the backend doesn't
    report. jax returns either a dict or a one-element list of dicts
    depending on version — both shapes are handled."""
    try:
        analysis = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else None
    if not isinstance(analysis, dict):
        return None
    out = {}
    for src, dst in (("flops", "flops"),
                     ("bytes accessed", "bytes_accessed"),
                     ("optimal_seconds", "optimal_seconds")):
        v = analysis.get(src)
        if isinstance(v, (int, float)) and v == v and v >= 0:
            out[dst] = float(v)
    return out or None


def device_memory_stats(device=None):
    """Best-effort ``device.memory_stats()`` as a JSON-safe dict of
    numeric fields (PJRT reports e.g. bytes_in_use / bytes_limit /
    peak_bytes_in_use on TPU/GPU); None where the backend doesn't
    report (CPU). Adds ``bytes_free`` (limit - in_use, the HBM
    headroom) when both sides are present."""
    try:
        if device is None:
            import jax
            device = jax.devices()[0]
        stats = device.memory_stats()
    except Exception:
        return None
    if not isinstance(stats, dict):
        return None
    out = {k: v for k, v in stats.items()
           if isinstance(v, (int, float)) and v == v}
    if not out:
        return None
    if "bytes_limit" in out and "bytes_in_use" in out:
        out["bytes_free"] = out["bytes_limit"] - out["bytes_in_use"]
    return out


def _call_site(skip=0):
    """Innermost stack frame outside this module, after skipping
    ``skip`` additional frames (the engine skips its own _compiled
    helper so attribution lands on the dispatch line that triggered
    the build)."""
    frames = [fr for fr in traceback.extract_stack()
              if os.path.basename(fr.filename) != _SELF]
    if not frames:
        return "<unknown>"
    idx = max(0, len(frames) - 1 - skip)
    fr = frames[idx]
    return f"{fr.filename}:{fr.lineno} ({fr.name})"


class CompileWatchdog:
    """Attributed compile log with a declared-warmup alarm.

    ``mode="flag"`` (default) records steady-state compiles and
    surfaces them in ``report()``; ``mode="raise"`` additionally
    raises CompileAfterWarmupError at the offending record() — the
    hard-fail setting for tests and canary deployments.
    """

    def __init__(self, mode="flag"):
        if mode not in ("flag", "raise"):
            raise ValueError(f"mode must be 'flag' or 'raise', got "
                             f"{mode!r}")
        self.mode = mode
        self._lock = threading.Lock()
        self._events = []
        self._warmed = False

    # ------------------------------------------------------- recording
    def record(self, key, signature="", call_site=None, skip=0):
        """Log one compile. ``key`` identifies the executable (the
        engine uses its AOT-table key), ``signature`` the abstract
        shapes it was built for; ``call_site`` defaults to the caller's
        file:line (``skip`` walks further out for wrapper helpers).
        Returns the event dict; raises in mode='raise' when warm."""
        if call_site is None:
            call_site = _call_site(skip=skip)
        with self._lock:
            event = {
                "seq": len(self._events),
                "key": key if isinstance(key, str) else repr(key),
                "signature": signature,
                "call_site": call_site,
                "steady_state": self._warmed,
                # post-compile device telemetry, attached via
                # annotate() once the executable exists (record() runs
                # BEFORE the build so mode="raise" prevents it)
                "cost": None,
                "memory": None,
            }
            self._events.append(event)
            warmed = self._warmed
        if warmed and self.mode == "raise":
            raise CompileAfterWarmupError(
                f"compile after declared warmup: key={event['key']} "
                f"signature={signature} at {call_site}")
        return event

    def annotate(self, seq, **extra):
        """Attach post-compile facts (device cost analysis, memory
        stats) to an already-recorded event by its ``seq``. JSON-safe
        values only — the events feed report() straight into bench
        artifacts."""
        with self._lock:
            self._events[seq].update(extra)

    def declare_warmup_complete(self):
        """From here on, every compile is a steady-state violation."""
        with self._lock:
            self._warmed = True

    def reopen_warmup(self):
        """Re-enter warmup (supervisor restart): the rebuilt AOT
        table's compiles are recovery work, not steady-state
        violations — the supervisor re-declares warmup once the replay
        drains, so the alarm re-arms the moment recovery completes.
        Already-flagged events keep their steady_state attribution."""
        with self._lock:
            self._warmed = False

    # -------------------------------------------------------- querying
    @property
    def warmed(self):
        return self._warmed

    @property
    def compiles(self):
        with self._lock:
            return len(self._events)

    def events(self):
        with self._lock:
            return [dict(e) for e in self._events]

    def steady_state_events(self):
        return [e for e in self.events() if e["steady_state"]]

    def signature_groups(self):
        """Compile signatures grouped by executable key — the feed for
        the analysis ``dynamic-shape-risk`` lint pass: one key compiled
        under more than one distinct abstract-shape signature means the
        same logical executable re-specialized per input shape (the
        python-int-shape-derived-from-traced-values recompile source),
        attributed by the recorded dispatch call-sites."""
        with self._lock:
            groups = {}
            for e in self._events:
                g = groups.setdefault(
                    e["key"], {"signatures": [], "call_sites": []})
                if e["signature"] not in g["signatures"]:
                    g["signatures"].append(e["signature"])
                if e["call_site"] not in g["call_sites"]:
                    g["call_sites"].append(e["call_site"])
            return groups

    def report(self):
        """JSON-ready summary — the bench artifact's ``watchdog``
        section and the test surface for the zero-recompile
        invariant."""
        events = self.events()
        steady = [e for e in events if e["steady_state"]]
        return {
            "warmed": self._warmed,
            "mode": self.mode,
            "compiles_total": len(events),
            "warmup_compiles": len(events) - len(steady),
            "steady_state_compiles": len(steady),
            "events": events,
            "steady_state_events": steady,
        }


@contextlib.contextmanager
def watch_jax_lowering(watchdog):
    """Patch the generic ``jax.stages.Lowered.compile`` AOT entry
    point so every lowering compiled inside the block is recorded in
    ``watchdog`` with its in_avals signature and call-site. Restores
    the original on exit; reentrant use nests harmlessly (each level
    records once — the patch chain unwinds in reverse)."""
    import jax

    cls = jax.stages.Lowered
    original = cls.compile

    def compile(self, *args, **kwargs):  # noqa: A002 - jax's name
        executable = original(self, *args, **kwargs)
        try:
            avals = getattr(self, "in_avals", None)
            signature = str(avals)[:400] if avals is not None else ""
        except Exception:
            signature = ""
        # the patched frame lives in this file and is filtered out of
        # the stack walk already, so skip=0 lands on the caller
        watchdog.record("jax.Lowered.compile", signature=signature)
        return executable

    cls.compile = compile
    try:
        yield watchdog
    finally:
        cls.compile = original
