"""Per-step serving ledger: the health observatory's flight data.

Every engine step appends ONE structured row — wall/dispatch/sync
seconds, queue and slot state, token/admission/shed deltas, paged-pool
block economy, compile flags — into a bounded ring. The ledger is the
black box the anomaly detectors (health.detectors) evaluate online and
the incident bundles (health.incidents) snapshot at capture time: when
a serve loop wedges, the last rows name the step it died on and what
the engine was doing there (the BENCH_r05 ">900s tunnel wedge" was
unattributable for exactly the lack of this record).

Rows are plain JSON-safe dicts; ``LEDGER_ROW_KEYS`` is the schema
contract (tests pin it — keys only get added, never renamed). The
ledger itself is dumb bounded storage: the ENGINE authors rows (it
owns the counters the deltas come from), detectors only read.
"""
import collections
import threading

# the per-step row schema the engine authors (tests/test_health.py pins
# this contract; incident_report.py renders a table from it)
LEDGER_ROW_KEYS = (
    "step",               # engine step id (1-based, monotone)
    "t",                  # wall-clock epoch seconds at row append
    "wall_s",             # step wall time (serving/step scope)
    "dispatch_s",         # delta wall spent ISSUING device work
    "sync_s",             # delta wall BLOCKED on device->host reads
    "queue_depth",        # queued requests after the step
    "queue_age_s",        # how long the queue head has waited
    "occupied_slots",     # live slots after the step
    "chunked_inflight",   # chunk plans still mid-prefill
    "admitted",           # requests admitted this step
    "tokens",             # tokens emitted this step
    "completed",          # requests retired this step
    "goodput_tokens",     # SLO-met tokens credited this step
    "prefill_tokens",     # prompt tokens computed this step
    "prefill_chunks",     # chunked-prefill dispatches this step
    "shed",               # requests load-shed this step
    "deprioritized",      # requests deferred this step
    "new_compiles",       # executables built this step
    "steady_compiles",    # of those, after declared warmup
    "slo_on",             # SLO targets configured (bool)
    "prefix_hit_rate",    # cumulative prefix-cache hit rate (None=n/a)
    "pool_free_blocks",   # paged pool economy (None on legacy pool)
    "pool_evictable_blocks",
    "pool_live_blocks",
    "conservation_ok",    # periodic audit verdict (None = not audited)
    "conservation_error",
    "cache_thrash",       # radix evict-then-reinsert events this step
    "pool_evictable_delta",  # evictable-block count change this step
)


class StepLedger:
    """Thread-safe bounded ring of per-step rows.

    ``keep`` bounds memory under serve-forever traffic (the same
    discipline as the flight recorder's completed ring); ``steps``
    counts every row ever appended, so ``steps - kept`` is the
    overwritten history.
    """

    def __init__(self, keep=512):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.keep = int(keep)
        self._rows = collections.deque(maxlen=self.keep)
        self._steps = 0
        self._lock = threading.Lock()

    def append(self, row):
        """Append one row. The ledger takes OWNERSHIP of the dict (no
        defensive copy — this runs on every engine step); readers get
        copies from rows()/last()."""
        with self._lock:
            self._rows.append(row)
            self._steps += 1

    @property
    def steps(self):
        """Rows ever appended (ring overwrites don't un-count)."""
        return self._steps

    @property
    def last_step_id(self):
        """The ``step`` field of the newest row; 0 before any step —
        the heartbeat's "last thing the engine finished" attribution."""
        with self._lock:
            return self._rows[-1]["step"] if self._rows else 0

    def __len__(self):
        with self._lock:
            return len(self._rows)

    def last(self):
        with self._lock:
            return dict(self._rows[-1]) if self._rows else None

    def rows(self, last=None):
        """The newest ``last`` rows (all kept rows when None), oldest
        first, as copies — safe to serialize while stepping."""
        with self._lock:
            rows = list(self._rows)
        if last is not None:
            rows = rows[-int(last):]
        return [dict(r) for r in rows]

    def tail(self, n):
        return self.rows(last=n)

    def as_dict(self, last=None):
        """The ``/debug/ledger`` JSON body."""
        rows = self.rows(last=last)
        return {
            "steps": self._steps,
            "kept": len(self),
            "keep": self.keep,
            "last_step": self.last_step_id,
            "rows": rows,
        }
