"""Online anomaly detectors over the serving step ledger.

Each detector watches the per-step rows (health.ledger.StepLedger) for
ONE failure signature and returns a machine-readable verdict dict the
moment it fires — the HealthMonitor then counts it
(``serving_anomalies_total{detector=...}``), emits a flight-recorder-
style marker span, and (debounced) captures an incident bundle.

The framework mirrors ``analysis.lint.register_lint_pass``: detectors
are classes registered under a name via :func:`register_detector`;
:func:`build_detectors` instantiates the whole registry (with optional
per-detector kwarg overrides, e.g.
``{"queue_stall": {"stall_steps": 8}}``), so projects can plug their
own detectors in and tests can tighten thresholds.

Built-in detectors (every threshold errs on the quiet side — a clean
bench run must fire NOTHING; a wedge is never subtle):

``step_time_spike``
    step wall time far beyond the rolling window's median (MAD-scaled
    robust z plus an absolute floor and a median multiple). Steps that
    compiled are exempt — compile time measures XLA, and the
    steady-state-compile detector owns those.
``queue_stall``
    queued work with NO progress of any kind (no admissions, no
    tokens, no chunks, no completions) for N consecutive steps — the
    it-is-wedged-but-still-stepping signature.
``goodput_collapse``
    windowed SLO-met tokens/sec falling off a cliff: the previous
    window was healthy (>= healthy_frac of the engine's peak windowed
    rate) and the current adjacent window delivers < drop_frac of it
    while work is pending. Gradual degradation under deliberate
    overload passes through intermediate windows and does NOT fire —
    that regime belongs to the admission policy, not the alarm.
``kv_block_leak``
    a failed periodic ``PagedKVPool`` conservation audit, or blocks
    still referenced while the engine is completely idle (free-list
    drift — the slow leak that eventually starves admission).
``steady_state_compile``
    any executable built after ``declare_warmup()`` — the compile
    watchdog's violation surfaced as a first-class anomaly instead of
    a flag a human must go read.
``cache_thrash``
    sustained prefix-cache evict-then-reinsert churn (the PR-13 cache
    observatory's thrash counter, per-step deltas summed over a
    rolling window) — the KV pool is smaller than the live prefix
    working set; ``/debug/cache``'s MRC says what more capacity buys.
"""
import collections

# detector registries by SCOPE: "engine" detectors watch one engine's
# per-step ledger rows (the PR-8 observatory), "fleet" detectors watch
# the fleet poller's per-poll rollup rows (observability.fleet) — one
# framework, two row vocabularies, and a HealthMonitor never
# instantiates a fleet detector (or vice versa) because build_detectors
# only reads its own scope
_SCOPES = {"engine": {}}
_DETECTORS = _SCOPES["engine"]   # legacy alias (engine scope)


def _scope(scope):
    return _SCOPES.setdefault(scope, {})


def register_detector(name, scope="engine"):
    """Register a detector class/factory under ``name`` (zero-required-
    arg constructible; keyword thresholds only). Re-registering
    replaces — tests stub detectors this way. The instance's ``name``
    attribute is stamped to match. ``scope`` namespaces the registry:
    engine detectors (default) evaluate per-step ledger rows, fleet
    detectors (``scope="fleet"``) evaluate per-poll fleet rows."""
    def deco(factory):
        factory.name = name
        _scope(scope)[name] = factory
        return factory
    return deco


def unregister_detector(name, scope="engine"):
    """Remove a registered detector (test cleanup)."""
    return _scope(scope).pop(name, None)


def detector_names(scope="engine"):
    """All registered detector names in ``scope``, sorted."""
    return sorted(_scope(scope))


def build_detectors(overrides=None, only=None, scope="engine"):
    """Instantiate every detector registered in ``scope`` (or the
    ``only`` subset), passing ``overrides[name]`` as constructor
    kwargs when present — the ServingConfig(health_detectors=...) /
    FleetPoller(detector_config=...) plumbing."""
    overrides = dict(overrides or {})
    reg = _scope(scope)
    names = detector_names(scope) if only is None else list(only)
    out = []
    for n in names:
        if n not in reg:
            raise ValueError(f"unknown detector {n!r}; registered in "
                             f"scope {scope!r}: {detector_names(scope)}")
        out.append(reg[n](**overrides.get(n, {})))
    return out


class Detector:
    """Base: ``observe(row, ledger)`` returns a verdict dict when the
    anomaly fires this step, else None. Detectors keep their own
    rolling state; they are called from the engine's stepping thread
    only."""

    name = "detector"

    def observe(self, row, ledger):
        raise NotImplementedError

    def _verdict(self, row, reason, **extra):
        return dict({"detector": self.name, "step": row["step"],
                     "reason": reason}, **extra)


def _median(xs):
    s = sorted(xs)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else 0.5 * (s[mid - 1] + s[mid])


@register_detector("step_time_spike")
class StepTimeSpike(Detector):
    """Step wall time spike vs rolling median, MAD-based.

    Fires when a (non-compiling) step's wall time exceeds ALL of:
    ``min_wall_s`` (absolute floor — millisecond jitter is not an
    incident), ``spike_factor`` x the window median, and
    median + ``k_mad`` x 1.4826 x MAD (the robust z-score). Needs
    ``min_steps`` clean samples first. After firing the window resets:
    a new plateau becomes the new baseline instead of refiring every
    step. The median/MAD pair refreshes every ``refresh_every`` steps
    (the baseline drifts slowly; re-sorting the window per step is
    pure per-step overhead the observatory must not add)."""

    def __init__(self, window=64, min_steps=24, k_mad=8.0,
                 spike_factor=6.0, min_wall_s=0.5, refresh_every=8):
        self.window = int(window)
        self.min_steps = int(min_steps)
        self.k_mad = float(k_mad)
        self.spike_factor = float(spike_factor)
        self.min_wall_s = float(min_wall_s)
        self.refresh_every = int(refresh_every)
        self._hist = collections.deque(maxlen=self.window)
        self._stats = None          # (median, mad, threshold)
        self._since_refresh = 0

    def _refresh(self):
        med = _median(self._hist)
        mad = _median([abs(x - med) for x in self._hist])
        threshold = max(self.min_wall_s,
                        self.spike_factor * med,
                        med + self.k_mad * 1.4826 * mad)
        self._stats = (med, mad, threshold)
        self._since_refresh = 0

    def observe(self, row, ledger):
        if row.get("new_compiles"):
            # compile steps measure XLA build time, not service — the
            # steady_state_compile detector owns post-warmup builds
            return None
        wall = float(row["wall_s"])
        if len(self._hist) >= self.min_steps:
            if self._stats is None \
                    or self._since_refresh >= self.refresh_every:
                self._refresh()
            self._since_refresh += 1
            med, mad, threshold = self._stats
            if wall > threshold:
                self._hist.clear()
                self._stats = None
                return self._verdict(
                    row,
                    f"step wall {wall * 1000.0:.1f}ms vs rolling "
                    f"median {med * 1000.0:.1f}ms",
                    wall_s=round(wall, 6),
                    rolling_median_s=round(med, 6),
                    rolling_mad_s=round(mad, 6),
                    threshold_s=round(threshold, 6))
        self._hist.append(wall)
        return None


@register_detector("queue_stall")
class QueueStall(Detector):
    """Queued work with zero progress for ``stall_steps`` consecutive
    steps. Progress = any admission, emitted token, prefill chunk, or
    completion; a full-but-decoding engine is NOT stalled. Fires once
    per stall episode (re-arms on the next progress)."""

    def __init__(self, stall_steps=32):
        self.stall_steps = int(stall_steps)
        self._streak = 0
        self._fired = False

    def observe(self, row, ledger):
        progress = (row["admitted"] or row["tokens"]
                    or row["prefill_chunks"] or row["completed"])
        if row["queue_depth"] > 0 and not progress:
            self._streak += 1
            if self._streak >= self.stall_steps and not self._fired:
                self._fired = True
                return self._verdict(
                    row,
                    f"{row['queue_depth']} queued request(s) with no "
                    f"admissions/tokens for {self._streak} steps",
                    steps_stalled=self._streak,
                    queue_depth=int(row["queue_depth"]),
                    queue_age_s=round(float(row["queue_age_s"]), 3))
        else:
            self._streak = 0
            self._fired = False
        return None


@register_detector("goodput_collapse")
class GoodputCollapse(Detector):
    """SLO-met tokens/sec cliff between adjacent windows.

    Tracks per-step goodput-token deltas in two adjacent ``window``-
    step windows. Fires when the previous window was HEALTHY (rate >=
    ``healthy_frac`` of the best windowed rate seen, with >=
    ``min_completions`` completions) and the current window collapses
    below ``drop_frac`` of it while work is still pending. The
    healthy-previous-window requirement is the false-positive gate: a
    deliberately overloaded FIFO engine degrades GRADUALLY through
    intermediate windows and never exhibits the healthy->collapsed
    cliff, while a true collapse (device wedged, SLO broken at once)
    does. Inert without SLO targets (no goodput to judge)."""

    def __init__(self, window=64, drop_frac=0.1, healthy_frac=0.5,
                 min_completions=4):
        self.window = int(window)
        self.drop_frac = float(drop_frac)
        self.healthy_frac = float(healthy_frac)
        self.min_completions = int(min_completions)
        self._rows = collections.deque(maxlen=2 * self.window)
        self._peak = 0.0

    @staticmethod
    def _rate(seg):
        wall = sum(w for _, w, _ in seg)
        good = sum(g for g, _, _ in seg)
        done = sum(c for _, _, c in seg)
        return (good / wall if wall > 0 else 0.0), done

    def observe(self, row, ledger):
        if not row.get("slo_on"):
            return None
        self._rows.append((float(row["goodput_tokens"]),
                           float(row["wall_s"]),
                           int(row["completed"])))
        if len(self._rows) < 2 * self.window:
            return None
        rows = list(self._rows)
        prev_rate, prev_done = self._rate(rows[:self.window])
        cur_rate, cur_done = self._rate(rows[self.window:])
        if prev_done >= self.min_completions and prev_rate > 0:
            self._peak = max(self._peak, prev_rate)
        work_pending = row["queue_depth"] > 0 or row["occupied_slots"] > 0
        if (work_pending
                and self._peak > 0
                and prev_done >= self.min_completions
                and cur_done >= self.min_completions
                and prev_rate >= self.healthy_frac * self._peak
                and cur_rate < self.drop_frac * prev_rate):
            self._rows.clear()
            return self._verdict(
                row,
                f"windowed goodput {cur_rate:.1f} tok/s collapsed "
                f"from {prev_rate:.1f} tok/s",
                window_steps=self.window,
                previous_rate_tps=round(prev_rate, 3),
                current_rate_tps=round(cur_rate, 3),
                peak_rate_tps=round(self._peak, 3))
        return None


@register_detector("kv_block_leak")
class KVBlockLeak(Detector):
    """Paged-pool block leak: a failed conservation audit (any step
    the engine ran one), or blocks still holding references while the
    engine is COMPLETELY idle (no queue, no slots, no chunk plans) —
    at idle every block must be free or parked evictable in the radix
    index. Inert on legacy-pool engines (pool fields are None). The
    idle branch fires once per leak episode."""

    def __init__(self):
        self._armed = True

    def observe(self, row, ledger):
        if row.get("conservation_ok") is False:
            return self._verdict(
                row, "paged pool conservation audit failed",
                audit_error=str(row.get("conservation_error")))
        live = row.get("pool_live_blocks")
        if live is None:
            return None
        idle = (row["queue_depth"] == 0 and row["occupied_slots"] == 0
                and row["chunked_inflight"] == 0)
        if idle and live > 0:
            if self._armed:
                self._armed = False
                return self._verdict(
                    row,
                    f"{live} block(s) still referenced with no live "
                    f"requests",
                    live_blocks=int(live),
                    free_blocks=int(row["pool_free_blocks"]),
                    evictable_blocks=int(row["pool_evictable_blocks"]))
        elif idle:
            self._armed = True
        return None


@register_detector("cache_thrash")
class CacheThrash(Detector):
    """Sustained prefix-cache thrash: the radix index keeps evicting
    paths and immediately recomputing them (the PR-13 cache
    observatory's evict-then-reinsert counter, surfaced per step as
    the ledger's ``cache_thrash`` delta). A rolling ``window``-step
    sum >= ``min_thrash`` means the pool is materially smaller than
    the live working set — the operator answer is the MRC in
    ``/debug/cache`` ("what would 2x capacity buy"). Conservative on
    purpose: occasional churn under admission pressure is the block
    economy WORKING; a clean bench run must fire nothing. Fires once
    per episode, re-arming after a thrash-free window. Inert on
    legacy-pool engines (field is None)."""

    def __init__(self, window=64, min_thrash=24):
        self.window = int(window)
        self.min_thrash = int(min_thrash)
        self._hist = collections.deque(maxlen=self.window)
        self._fired = False

    def observe(self, row, ledger):
        thrash = row.get("cache_thrash")
        if thrash is None:
            return None
        self._hist.append(int(thrash))
        total = sum(self._hist)
        if total >= self.min_thrash:
            if not self._fired:
                self._fired = True
                return self._verdict(
                    row,
                    f"{total} evict-then-reinsert event(s) over the "
                    f"last {len(self._hist)} steps — KV pool smaller "
                    f"than the live prefix working set",
                    thrash_events=int(total),
                    window_steps=len(self._hist),
                    evictable_blocks=row.get("pool_evictable_blocks"),
                    free_blocks=row.get("pool_free_blocks"))
        elif total == 0:
            self._fired = False
        return None


@register_detector("steady_state_compile")
class SteadyStateCompileAnomaly(Detector):
    """The compile watchdog's zero-recompile invariant surfaced as an
    anomaly: any executable built after declared warmup fires (per
    step, with the count) — the attribution details live in the
    incident bundle's watchdog section."""

    def observe(self, row, ledger):
        n = int(row.get("steady_compiles") or 0)
        if n > 0:
            return self._verdict(
                row, f"{n} compile(s) after declared warmup",
                compiles=n)
        return None
