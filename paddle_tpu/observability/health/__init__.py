"""Serving health observatory: per-step ledger, online anomaly
detectors, black-box incident capture.

PRs 3-4 made the serving engine richly OBSERVABLE (metrics registry,
chrome spans, flight recorder, SLO tracker, compile watchdog); this
package makes it SELF-monitoring — the closed loop production serving
stacks run:

  * **ledger.StepLedger** — a bounded ring of per-step structured
    rows appended by the engine (wall/dispatch/sync seconds, queue and
    slot state, token/shed deltas, paged-pool block economy, compile
    flags); ``/debug/ledger`` serves it, incident bundles snapshot it;
  * **detectors** — a pluggable ``register_detector`` framework
    (mirroring analysis.lint.register_lint_pass) evaluated every step:
    step-time spike (rolling-median MAD), queue stall, goodput
    collapse, KV-block leak, steady-state compile; each firing
    increments ``serving_anomalies_total{detector}`` and drops a
    ``health/<detector>`` marker span into the host timeline;
  * **incidents.IncidentRecorder / HealthMonitor** — on (debounced)
    firing, a JSON incident bundle (ledger tail, metrics snapshot,
    active request traces, span tail, watchdog report, verdict) lands
    on disk with keep-last-N rotation, and ``/debug/health`` returns
    ``{healthy, detectors, last_incident}`` — the per-replica signal
    the ROADMAP direction-#5 router polls.

Engine wiring: ``ServingConfig(health=True)`` (default; env gate
``PADDLE_HEALTH=0``), ``health_audit_every=`` for the periodic paged-
pool conservation audit (its cost visible as a ``serving/health_audit``
host span), ``incident_dir=`` to enable bundle capture
(``PADDLE_INCIDENT_DIR``), ``health_detectors=`` for per-detector
threshold overrides. ``tools/incident_report.py`` pretty-prints a
bundle.
"""
from .detectors import (  # noqa: F401
    Detector, GoodputCollapse, KVBlockLeak, QueueStall,
    SteadyStateCompileAnomaly, StepTimeSpike, build_detectors,
    detector_names, register_detector, unregister_detector,
)
from .incidents import (  # noqa: F401
    INCIDENT_KEYS, INCIDENT_SCHEMA, HealthMonitor, IncidentRecorder,
    disabled_health_summary,
)
from .ledger import LEDGER_ROW_KEYS, StepLedger  # noqa: F401
