"""Black-box incident capture + the health monitor that drives it.

``IncidentRecorder`` writes one JSON bundle per (debounced) detector
firing — last-K ledger rows, metrics snapshot, active request traces,
host-span tail, watchdog report, the detector's verdict — to a
directory with keep-last-N rotation, so the evidence of WHAT the
engine was doing at the moment of anomaly survives the process (the
flight-data-recorder answer to BENCH_r05's unattributable wedge).

``HealthMonitor`` is the per-engine orchestrator: the engine feeds it
one ledger row per step; it appends to the ledger, evaluates every
detector, and on each firing (1) increments
``serving_anomalies_total{detector=...}``, (2) emits a
``health/<detector>`` marker span into the host-span recorder (visible
in the chrome trace next to the step it fired on), and (3) captures an
incident bundle when the per-detector debounce allows. ``report()`` is
the ``/debug/health`` body — ``{healthy, detectors, last_incident}``,
the per-replica signal a scale-out router polls (ROADMAP direction
#5); ``summary()`` is the lighter ``snapshot()["health"]`` section.
"""
import itertools
import json
import os
import threading
import time

from ..tracing import default_recorder
from .detectors import build_detectors
from .ledger import StepLedger

INCIDENT_SCHEMA = "paddle_tpu.health.incident/v1"

# bundle sections every incident carries (tests pin this contract;
# tools/incident_report.py renders from it). ``chaos`` is the active
# FaultPlan + fault log when the engine runs under the fault-injection
# harness (None otherwise) — a chaos-found incident is replayable from
# the bundle alone. ``replica`` is the writing engine's identity
# (replica_id / uptime) — a bundle collected off one member of a
# fleet stays attributable after the fact. ``traces`` is the
# assembled distributed traces (ISSUE 18) of every request in flight
# at capture time — the anomaly's victims arrive with their
# cross-replica critical path already decomposed.
INCIDENT_KEYS = (
    "schema", "written_at", "detector", "verdict", "ledger_tail",
    "metrics", "watchdog", "requests", "spans_tail", "health",
    "chaos", "replica", "traces", "tenants",
)


def disabled_health_summary():
    """The ``snapshot()["health"]`` section of an engine built with
    health=False — same key set as a live summary, so the schema
    contract holds either way."""
    return {"enabled": False, "healthy": True, "anomalies_total": 0,
            "detectors": {}, "incidents_written": 0,
            "last_incident": None, "ledger_steps": 0,
            "degraded": False, "draining": False, "restarts": 0,
            "replica_id": None, "uptime_s": None}


class IncidentRecorder:
    """Debounced incident-bundle writer with keep-last-N rotation.

    ``debounce_s`` bounds disk churn per detector (the first firing of
    an episode captures; a flapping detector doesn't write a bundle
    per step); ``keep_last`` bounds the DIRECTORY — rotation prunes
    the oldest ``incident_*.json`` regardless of which recorder wrote
    them, so a long-lived fleet's incident dir never grows without
    bound. Capture is best-effort everywhere: a failing context
    callable contributes an error stub, never an exception into the
    serve loop."""

    def __init__(self, directory, keep_last=16, ledger_tail=64,
                 span_tail=120, debounce_s=60.0, clock=time.time):
        if keep_last < 1:
            raise ValueError("keep_last must be >= 1")
        self.directory = str(directory)
        self.keep_last = int(keep_last)
        self.ledger_tail = int(ledger_tail)
        self.span_tail = int(span_tail)
        self.debounce_s = float(debounce_s)
        self._clock = clock
        self._last = {}
        self._seq = itertools.count()
        self._lock = threading.Lock()
        self.written = 0
        self.last_path = None

    def should_capture(self, detector):
        with self._lock:
            last = self._last.get(detector)
        return last is None or (self._clock() - last) >= self.debounce_s

    def _section(self, context, key):
        fn = context.get(key)
        if fn is None:
            return None
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 - capture must not raise
            return {"error": f"{type(e).__name__}: {e}"}

    def capture(self, detector, verdict, ledger, context,
                health_report=None):
        """Write one bundle; returns its path. ``context`` maps section
        names (metrics / watchdog / requests / spans_tail) to zero-arg
        callables evaluated NOW — the moment-of-anomaly snapshot."""
        with self._lock:
            self._last[detector] = self._clock()
            seq = next(self._seq)
        bundle = {
            "schema": INCIDENT_SCHEMA,
            "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime()),
            "detector": str(detector),
            "verdict": dict(verdict),
            "ledger_tail": ledger.rows(last=self.ledger_tail)
            if ledger is not None else [],
            "metrics": self._section(context, "metrics"),
            "watchdog": self._section(context, "watchdog"),
            "requests": self._section(context, "requests"),
            "spans_tail": self._section(context, "spans_tail"),
            "health": health_report,
            "chaos": self._section(context, "chaos"),
            "replica": self._section(context, "replica"),
            "traces": self._section(context, "traces"),
            "tenants": self._section(context, "tenants"),
        }
        os.makedirs(self.directory, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        fname = f"incident_{stamp}_{seq:03d}_{detector}.json"
        path = os.path.join(self.directory, fname)
        with open(path, "w") as fh:
            json.dump(bundle, fh, indent=1, default=str)
        with self._lock:
            self.written += 1
            self.last_path = path
        self._rotate()
        return path

    def _rotate(self):
        try:
            files = sorted(f for f in os.listdir(self.directory)
                           if f.startswith("incident_")
                           and f.endswith(".json"))
        except OSError:
            return
        for f in files[:-self.keep_last]:
            try:
                os.unlink(os.path.join(self.directory, f))
            except OSError:
                pass

    def list_incidents(self):
        try:
            return sorted(
                os.path.join(self.directory, f)
                for f in os.listdir(self.directory)
                if f.startswith("incident_") and f.endswith(".json"))
        except OSError:
            return []


class HealthMonitor:
    """Per-engine health orchestrator: ledger + detectors + anomaly
    accounting + (optional) incident capture.

    ``registry`` hosts ``serving_anomalies_total{detector}`` and
    ``serving_detector_errors_total{detector}`` (a broken detector is
    counted and skipped, never allowed to take down the serve loop).
    ``context`` maps incident-bundle section names to zero-arg
    callables the engine provides (metrics snapshot, watchdog report,
    request traces, span tail)."""

    def __init__(self, registry, ledger_keep=512, detectors=None,
                 detector_config=None, incidents=None, recorder=None,
                 context=None, clock=time.perf_counter):
        self.ledger = StepLedger(keep=ledger_keep)
        self.detectors = build_detectors(detector_config) \
            if detectors is None else list(detectors)
        self.incidents = incidents
        self._recorder = recorder if recorder is not None \
            else default_recorder()
        self._context = dict(context or {})
        self._clock = clock
        self._c_anomalies = registry.counter(
            "serving_anomalies_total",
            "health-detector firings over the step ledger",
            labelnames=("detector",))
        self._c_errors = registry.counter(
            "serving_detector_errors_total",
            "health detectors that raised while evaluating a step "
            "(the detector is skipped for that step, never fatal)",
            labelnames=("detector",))
        self._state = {}
        self._resolved_total = 0   # anomalies acknowledged-recovered
        self._resilience_fn = None  # engine's degraded/draining state
        self._identity_fn = None    # engine's replica identity
        self._lock = threading.Lock()

    def attach_resilience(self, state_fn):
        """Attach the engine's resilience state (``{"degraded",
        "draining", "restarts"}``) so ``/debug/health`` tells the
        router the replica's TRUE serving posture, not just its
        anomaly history."""
        self._resilience_fn = state_fn

    def attach_identity(self, identity_fn):
        """Attach the engine's replica identity report (``{
        "replica_id", "uptime_s", ...}``) so ``/debug/health`` and
        ``snapshot()["health"]`` name WHICH replica they describe —
        the attribution a fleet poller's merged view depends on."""
        self._identity_fn = identity_fn

    def _identity(self):
        if self._identity_fn is None:
            return {"replica_id": None, "uptime_s": None}
        return self._identity_fn()

    def _resilience(self):
        if self._resilience_fn is None:
            return {"degraded": False, "draining": False, "restarts": 0}
        return self._resilience_fn()

    def resolve(self):
        """Mark every anomaly fired so far RECOVERED (the supervisor
        calls this when its restart's replay set drains): ``healthy``
        goes back to true unless NEW anomalies fire. The cumulative
        firing counters are untouched — resolution is a health-status
        fact, not an eraser."""
        with self._lock:
            self._resolved_total = sum(
                st["fired"] for st in self._state.values())

    # ------------------------------------------------------- stepping
    def observe(self, row):
        """Feed one ledger row; returns the verdicts that fired (often
        empty). Called from the engine's stepping thread."""
        self.ledger.append(row)
        fired = []
        for det in self.detectors:
            try:
                verdict = det.observe(row, self.ledger)
            except Exception:  # noqa: BLE001 - detectors can't be fatal
                self._c_errors.labels(det.name).inc()
                continue
            if verdict:
                self._fire(det.name, verdict)
                fired.append(verdict)
        return fired

    def _fire(self, name, verdict):
        self._c_anomalies.labels(name).inc()
        # marker span at the firing instant: the anomaly is visible in
        # the chrome/Perfetto timeline right next to the step it hit
        args = {k: v for k, v in verdict.items()
                if isinstance(v, (int, float, str, bool))}
        self._recorder.record(f"health/{name}", self._clock(), 0.0,
                              args=args)
        # state FIRST, so the incident bundle's health section already
        # reflects this firing (healthy: false, detector counted)
        with self._lock:
            st = self._state.setdefault(
                name, {"fired": 0, "last_step": None,
                       "last_verdict": None, "last_incident": None})
            st["fired"] += 1
            st["last_step"] = verdict.get("step")
            st["last_verdict"] = dict(verdict)
        if self.incidents is not None \
                and self.incidents.should_capture(name):
            try:
                incident = self.incidents.capture(
                    name, verdict, self.ledger, self._context,
                    health_report=self.summary())
            except Exception:  # noqa: BLE001 - capture is best-effort
                incident = None
            if incident is not None:
                with self._lock:
                    self._state[name]["last_incident"] = incident

    # ------------------------------------------------------- querying
    @property
    def anomalies_total(self):
        with self._lock:
            return sum(st["fired"] for st in self._state.values())

    @property
    def unresolved_total(self):
        """Anomalies fired since the last supervisor-declared
        recovery (= all of them when nothing ever resolved)."""
        with self._lock:
            total = sum(st["fired"] for st in self._state.values())
            return max(0, total - self._resolved_total)

    @property
    def healthy(self):
        """No unresolved anomalies AND not currently degraded — the
        bar a router's readiness poll should use."""
        return self.unresolved_total == 0 \
            and not self._resilience()["degraded"]

    def detector_counts(self):
        """{detector name: firings} for EVERY configured detector
        (zeros included — the detector list is part of the surface)."""
        with self._lock:
            return {d.name: self._state.get(d.name, {}).get("fired", 0)
                    for d in self.detectors}

    def report(self):
        """The ``/debug/health`` JSON body — the per-replica health
        signal a scale-out router polls."""
        with self._lock:
            detectors = {
                d.name: dict(self._state.get(
                    d.name, {"fired": 0, "last_step": None,
                             "last_verdict": None,
                             "last_incident": None}))
                for d in self.detectors}
        total = sum(st["fired"] for st in detectors.values())
        with self._lock:
            resolved = self._resolved_total
        res = self._resilience()
        ident = self._identity()
        unresolved = max(0, total - resolved)
        return {
            "healthy": unresolved == 0 and not res["degraded"],
            # which replica this health body describes (the fleet
            # poller's merged view keys on it)
            "replica_id": ident.get("replica_id"),
            "uptime_s": ident.get("uptime_s"),
            "anomalies_total": total,
            "anomalies_resolved": resolved,
            # the router-facing replica posture: degraded while a
            # supervisor restart's replay is still draining, draining
            # during a graceful engine drain, restarts cumulative
            "degraded": res["degraded"],
            "draining": res["draining"],
            "restarts": res["restarts"],
            "detectors": detectors,
            "last_incident": self.incidents.last_path
            if self.incidents is not None else None,
            "incidents_written": self.incidents.written
            if self.incidents is not None else 0,
            "ledger": {"steps": self.ledger.steps,
                       "kept": len(self.ledger),
                       "last_step": self.ledger.last_step_id},
        }

    def summary(self):
        """The ``snapshot()["health"]`` section (lighter than
        report(): firing counts only, no verdict payloads)."""
        total = self.anomalies_total
        res = self._resilience()
        ident = self._identity()
        return {
            "enabled": True,
            "healthy": self.healthy,
            "replica_id": ident.get("replica_id"),
            "uptime_s": ident.get("uptime_s"),
            "anomalies_total": total,
            "detectors": self.detector_counts(),
            "incidents_written": self.incidents.written
            if self.incidents is not None else 0,
            "last_incident": self.incidents.last_path
            if self.incidents is not None else None,
            "ledger_steps": self.ledger.steps,
            "degraded": res["degraded"],
            "draining": res["draining"],
            "restarts": res["restarts"],
        }

    def debug_ledger(self):
        """The ``/debug/ledger`` JSON body."""
        return self.ledger.as_dict()
