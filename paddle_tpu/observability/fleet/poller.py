"""Resilient multi-replica scrape poller.

``FleetPoller`` turns N per-replica debug surfaces (``/metrics.json``
+ ``/debug/health`` + ``/debug/state``, the endpoints every
``ServingEngine.serve_metrics()`` already exposes) into ONE fleet
view, with the failure discipline a fleet layer must have because
replicas die mid-scrape as a matter of course:

  * **per-replica timeout** — one wedged replica delays its own
    scrape, never the cycle (replicas scrape in parallel threads);
  * **exponential backoff with deterministic jitter** — a failing
    replica is re-probed at ``backoff_base_s * 2^(failures-1)``
    stretched by up to ``backoff_jitter`` (capped), so a dead host
    doesn't eat a timeout per cycle forever. The jitter fraction is
    a pure function of ``(jitter_seed, replica, failure count)`` —
    no global ``random`` state — so N pollers watching a bounced
    fleet de-synchronize their re-probes (different seeds spread
    out) while any single poller stays exactly reproducible;
  * **staleness marking** — every replica carries ``last_seen``; an
    ``up`` replica not successfully scraped within ``stale_after_s``
    is marked ``stale`` (distrust the numbers, don't evict yet);
  * **eviction / readmission verdicts** — ``down_after`` consecutive
    scrape failures evict (verdict ``down``); the next successful
    scrape readmits (``up``). This is exactly the health-poll-driven
    replica lifecycle the ROADMAP direction-#2 router spec calls for
    — the router will consume these verdicts, not reimplement them.

Every completed poll cycle appends one fleet row (``FLEET_ROW_KEYS``)
to a bounded ledger and runs the ``scope="fleet"`` detectors over it
(``replica_flap`` / ``fleet_goodput_collapse`` / ``load_skew`` — the
PR-8 ``register_detector`` framework, fleet scope). Firings count in
``fleet_anomalies_total{detector}`` on the poller's own registry and
drop ``fleet/<detector>`` marker spans into the host timeline.

Targets are a static replica list — ``host:port`` strings, dicts
``{"id": ..., "url": ...}`` — or a JSON registry file via
:meth:`FleetPoller.from_registry`. Scrape transport is injectable
(``fetch=``) so tests drive the whole lifecycle without sockets.
"""
import json
import random
import threading
import time
import urllib.request

from ..health.detectors import build_detectors


def backoff_jitter_unit(seed, who, attempt):
    """Deterministic unit-interval jitter fraction for backoff
    spreading: a pure function of ``(seed, who, attempt)`` via a
    local ``random.Random`` stream — the global ``random`` state is
    never touched (PR-9 discipline), so jittered backoff is exactly
    reproducible per poller and de-correlated across pollers with
    different seeds. The serving router reuses this for its retry
    backoff."""
    return random.Random(f"{seed}:{who}:{attempt}").random()
from ..health.ledger import StepLedger
from ..registry import MetricsRegistry, prometheus_text_from_snapshots
from ..tracing import default_recorder
from . import rollup

__all__ = ["FleetPoller", "ReplicaState", "FLEET_ROW_KEYS",
           "FLEET_TENANT_ROW_KEYS"]

# the per-poll fleet row the fleet detectors evaluate (``step`` is the
# poll sequence number, so the shared Detector/ledger machinery from
# the engine observatory applies unchanged)
FLEET_ROW_KEYS = (
    "step",           # poll cycle number (1-based, monotone)
    "t",              # wall-clock epoch seconds at cycle end
    "dt_s",           # seconds since the previous cycle
    "size", "up", "stale", "down",
    "transitions",    # [{replica, from, to}] verdict changes this cycle
    "queue_depths",   # {replica_id: queued} over non-down replicas
    "queue_depth",    # their sum
    "goodput_total",  # fleet cumulative SLO-met tokens (last known)
    "goodput_delta",  # of those, new since the previous cycle
    "work_pending",   # any replica reports queued work or occupancy
    "tenants",        # {tenant: per-cycle fairness facts} (see below)
)

# per-tenant per-cycle facts inside row["tenants"]: cumulative fleet
# sums of the tenant-labelled counters differenced between cycles
# (the noisy_neighbor / tenant_starvation detectors' evidence), plus
# the live queue depth from the replicas' /debug/state tenant sections
FLEET_TENANT_ROW_KEYS = (
    "tokens_delta", "requests_delta", "completed_delta",
    "attained_delta", "violated_delta", "queued",
)

_TENANT_ROW_COUNTERS = (
    ("tokens_delta", "serving_tenant_tokens_out_total"),
    ("requests_delta", "serving_tenant_requests_total"),
    ("completed_delta", "serving_tenant_completed_total"),
    ("attained_delta", "serving_tenant_slo_attained_total"),
    ("violated_delta", "serving_tenant_slo_violations_total"),
)


def _default_fetch(url, timeout):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read().decode("utf-8"))


def _normalize_url(target):
    t = str(target)
    if not t.startswith("http://") and not t.startswith("https://"):
        t = "http://" + t
    return t.rstrip("/")


class ReplicaState:
    """One replica's availability bookkeeping + last-known scrape
    bodies. ``verdict`` reads ``down`` until the first verdict is
    established; internal transitions FROM the never-polled state are
    not reported (a fresh poller starting against a live fleet is not
    a flap)."""

    def __init__(self, replica_id, url):
        self.configured_id = replica_id
        self.replica_id = replica_id or url.split("//", 1)[-1]
        self.url = url
        self._verdict = None          # None until first established
        self.last_seen = None         # poller-clock time of last success
        self.consecutive_failures = 0
        self.polls = 0
        self.failures = 0
        self.evictions = 0
        self.readmissions = 0
        self.backoff_until = 0.0
        self.scrape_s = None
        self.last_error = None
        self.metrics = None           # last-known /metrics.json body
        self.health = None            # last-known /debug/health body
        self.state = None             # last-known /debug/state body
        self.step_rate = None
        self._prev_steps = None
        self._prev_steps_t = None

    @property
    def verdict(self):
        return self._verdict if self._verdict is not None else "down"

    def set_verdict(self, verdict):
        """Returns the transition record when the verdict CHANGED
        between established states, else None."""
        old = self._verdict
        self._verdict = verdict
        if old is None or old == verdict:
            return None
        return {"replica": self.replica_id, "from": old, "to": verdict}


class FleetPoller:
    """Poll a static replica list; aggregate availability, posture and
    metrics into the ``FleetSnapshot``. ``start()`` runs the cycle on
    a daemon thread every ``interval_s``; ``poll_once()`` drives it
    synchronously (tests, one-shot CLIs)."""

    def __init__(self, targets, interval_s=2.0, timeout_s=1.0,
                 stale_after_s=None, down_after=3, backoff_base_s=None,
                 backoff_max_s=None, backoff_jitter=0.25,
                 jitter_seed=0, ledger_keep=512, registry=None,
                 detector_config=None, fetch=None,
                 clock=time.monotonic):
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.stale_after_s = float(stale_after_s) \
            if stale_after_s is not None else 3.0 * self.interval_s
        self.down_after = int(down_after)
        if self.down_after < 1:
            raise ValueError("down_after must be >= 1")
        self.backoff_base_s = float(backoff_base_s) \
            if backoff_base_s is not None else self.interval_s
        self.backoff_max_s = float(backoff_max_s) \
            if backoff_max_s is not None else 8.0 * self.interval_s
        self.backoff_jitter = float(backoff_jitter)
        if not 0.0 <= self.backoff_jitter <= 1.0:
            raise ValueError(
                f"backoff_jitter must be in [0, 1], "
                f"got {backoff_jitter}")
        self.jitter_seed = jitter_seed
        self._clock = clock
        self._fetch = fetch if fetch is not None else _default_fetch
        self.replicas = []
        seen = set()
        for rid, url in self.parse_targets(targets):
            if url in seen:
                continue
            seen.add(url)
            self.replicas.append(ReplicaState(rid, url))
        if not self.replicas:
            raise ValueError("FleetPoller needs at least one target")
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._c_scrapes = self.registry.counter(
            "fleet_scrapes_total", "scrape attempts by outcome",
            labelnames=("outcome",))
        self._c_anomalies = self.registry.counter(
            "fleet_anomalies_total",
            "fleet-detector firings over the poll ledger",
            labelnames=("detector",))
        self._c_detector_errors = self.registry.counter(
            "fleet_detector_errors_total",
            "fleet detectors that raised while evaluating a poll "
            "(skipped for that cycle, never fatal)",
            labelnames=("detector",))
        self._g_replicas = self.registry.gauge(
            "fleet_replicas", "replica count by availability verdict",
            labelnames=("verdict",))
        self.detectors = build_detectors(detector_config, scope="fleet")
        self.ledger = StepLedger(keep=ledger_keep)
        self._recorder = default_recorder()
        self._detector_state = {}
        self._lock = threading.RLock()
        self._polls = 0
        self._last_poll_t = None
        self._prev_goodput = None
        self._prev_tenants = None   # tenant -> cumulative fleet sums
        self._stop = threading.Event()
        self._thread = None

    # ----------------------------------------------------- targets
    @staticmethod
    def parse_targets(targets):
        """[(replica_id_or_None, base_url)] from ``host:port`` / URL
        strings or ``{"id": ..., "url"|"target": ...}`` dicts."""
        out = []
        for t in targets:
            if isinstance(t, dict):
                url = t.get("url") or t.get("target")
                if not url:
                    raise ValueError(f"registry entry without url: {t}")
                out.append((t.get("id") or t.get("replica_id"),
                            _normalize_url(url)))
            else:
                out.append((None, _normalize_url(t)))
        return out

    @classmethod
    def from_registry(cls, path, **kw):
        """Build a poller from a JSON registry file: either a plain
        list of targets or ``{"replicas": [...]}`` with ``host:port``
        strings / ``{"id", "url"}`` entries."""
        with open(path) as fh:
            doc = json.load(fh)
        targets = doc.get("replicas", doc) if isinstance(doc, dict) \
            else doc
        return cls(targets, **kw)

    # ----------------------------------------------------- scraping
    def _scrape(self, st):
        """One replica's three-endpoint scrape. ``/metrics.json`` is
        the availability probe — its failure fails the scrape;
        ``/debug/health`` and ``/debug/state`` are best-effort (an
        engine mid-close may answer some routes and not others — the
        replica entry just carries None for the missing posture)."""
        t0 = time.perf_counter()
        metrics = self._fetch(st.url + "/metrics.json", self.timeout_s)
        if not isinstance(metrics, dict):
            raise ValueError("non-object /metrics.json body")
        health = state = None
        try:
            health = self._fetch(st.url + "/debug/health",
                                 self.timeout_s)
        except Exception:  # noqa: BLE001 - best-effort posture
            pass
        try:
            state = self._fetch(st.url + "/debug/state", self.timeout_s)
        except Exception:  # noqa: BLE001 - best-effort posture
            pass
        return {"metrics": metrics, "health": health, "state": state,
                "scrape_s": time.perf_counter() - t0}

    def _apply_success(self, st, result, now):
        st.polls += 1
        st.consecutive_failures = 0
        st.last_seen = now
        st.scrape_s = result["scrape_s"]
        st.last_error = None
        st.metrics = result["metrics"]
        if result["health"] is not None:
            st.health = result["health"]
        if result["state"] is not None:
            st.state = result["state"]
        # learn the replica's self-reported identity (configured ids
        # win only until the replica says who it actually is)
        reported = ((st.state or {}).get("replica") or {}) \
            .get("replica_id") \
            or rollup.build_info_labels(st.metrics).get("replica")
        if reported:
            st.replica_id = str(reported)
        # step rate between the last two successful scrapes
        steps = ((st.health or {}).get("ledger") or {}).get("steps")
        if steps is not None and st._prev_steps is not None \
                and now > st._prev_steps_t:
            st.step_rate = max(0.0, (steps - st._prev_steps)
                               / (now - st._prev_steps_t))
        if steps is not None:
            st._prev_steps = steps
            st._prev_steps_t = now
        self._c_scrapes.labels("ok").inc()
        tr = st.set_verdict("up")
        if tr is not None and tr["from"] == "down":
            st.readmissions += 1
        return tr

    def _apply_failure(self, st, exc, now):
        st.polls += 1
        st.failures += 1
        st.consecutive_failures += 1
        st.last_error = f"{type(exc).__name__}: {exc}"[:160]
        # exponential backoff stretched by deterministic seeded jitter
        # (a pure function of seed/replica/failure-count — N pollers
        # watching the same bounced fleet re-probe spread out instead
        # of in lockstep, yet each poller is exactly reproducible)
        stretch = 1.0 + self.backoff_jitter * backoff_jitter_unit(
            self.jitter_seed, st.replica_id or st.url,
            st.consecutive_failures)
        backoff = min(self.backoff_max_s,
                      self.backoff_base_s
                      * (2 ** (st.consecutive_failures - 1))
                      * stretch)
        st.backoff_until = now + backoff
        self._c_scrapes.labels("error").inc()
        if st.consecutive_failures >= self.down_after:
            tr = st.set_verdict("down")
            if tr is not None:
                st.evictions += 1
            return tr
        return None

    def poll_once(self):
        """One full poll cycle: scrape every non-backed-off replica in
        parallel, apply verdicts, append the fleet row, run the fleet
        detectors. Returns the verdicts that fired (often empty)."""
        now = self._clock()
        with self._lock:
            due = [st for st in self.replicas
                   if now >= st.backoff_until]
        results = {}

        def scrape(st):
            try:
                results[st.url] = ("ok", self._scrape(st))
            except Exception as e:  # noqa: BLE001 - per-replica fate
                results[st.url] = ("error", e)

        threads = [threading.Thread(target=scrape, args=(st,),
                                    daemon=True,
                                    name=f"fleet-scrape-{st.replica_id}")
                   for st in due]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=self.timeout_s * 4 + 1.0)
        now = self._clock()
        transitions = []
        with self._lock:
            for st in due:
                outcome = results.get(st.url)
                if outcome is None:      # scrape thread still wedged
                    outcome = ("error",
                               TimeoutError("scrape thread wedged"))
                kind, payload = outcome
                tr = self._apply_success(st, payload, now) \
                    if kind == "ok" \
                    else self._apply_failure(st, payload, now)
                if tr is not None:
                    transitions.append(tr)
            # staleness pass over EVERY replica (backed-off included):
            # an up replica we haven't successfully scraped within the
            # window is stale — numbers distrusted, not yet evicted
            for st in self.replicas:
                if st.verdict == "up" and st.last_seen is not None \
                        and now - st.last_seen > self.stale_after_s:
                    tr = st.set_verdict("stale")
                    if tr is not None:
                        transitions.append(tr)
            self._polls += 1
            dt = (now - self._last_poll_t) \
                if self._last_poll_t is not None else self.interval_s
            self._last_poll_t = now
            row = self._fleet_row(now, dt, transitions)
            for verdict in ("up", "stale", "down"):
                self._g_replicas.labels(verdict).set(row[verdict])
        fired = self._observe(row)
        return fired

    def _fleet_row(self, now, dt, transitions):
        verdicts = [st.verdict for st in self.replicas]
        depths = {}
        work_pending = False
        goodput = 0.0
        for st in self.replicas:
            if st.metrics is not None:
                goodput += rollup.counter_value(
                    st.metrics, "serving_goodput_tokens_total") or 0.0
            if st.verdict == "down" or st.state is None:
                continue
            q = st.state.get("queue_depth")
            if q is not None:
                depths[st.replica_id] = int(q)
            occ = st.state.get("slot_occupancy") or 0
            if (q or 0) > 0 or occ > 0:
                work_pending = True
        prev_good = self._prev_goodput
        self._prev_goodput = goodput
        # per-tenant fleet sums this cycle (cumulative, last-known):
        # differenced against the previous cycle's sums into the
        # fairness deltas the tenant detectors judge. A replica that
        # died keeps contributing its last-known totals, so deltas
        # never go negative on eviction.
        cum = {}
        queued = {}
        for st in self.replicas:
            if st.metrics is not None:
                for key, family in _TENANT_ROW_COUNTERS:
                    fam = st.metrics.get(family)
                    for labels, v in ((fam or {}).get("values")
                                      or {}).items():
                        if not labels.startswith("tenant=") \
                                or not isinstance(v, (int, float)):
                            continue
                        t = labels[len("tenant="):]
                        cell = cum.setdefault(
                            t, dict.fromkeys(
                                (k for k, _ in _TENANT_ROW_COUNTERS),
                                0.0))
                        cell[key] += v
            if st.verdict != "down" and st.state is not None:
                sec = st.state.get("tenants") or {}
                for t, entry in (sec.get("tenants") or {}).items():
                    queued[t] = queued.get(t, 0) \
                        + (entry.get("queued") or 0)
        prev_ten = self._prev_tenants
        self._prev_tenants = cum
        tenants = {}
        for t in sorted(set(cum) | set(queued)):
            cell = cum.get(t) or {}
            prev = (prev_ten or {}).get(t) or {}
            fact = {key: max(0.0, (cell.get(key) or 0.0)
                             - (prev.get(key) or 0.0))
                    if prev_ten is not None else 0.0
                    for key, _ in _TENANT_ROW_COUNTERS}
            fact["queued"] = int(queued.get(t, 0))
            tenants[t] = fact
        return {
            "step": self._polls,
            "t": time.time(),
            "dt_s": round(dt, 6),
            "size": len(self.replicas),
            "up": sum(v == "up" for v in verdicts),
            "stale": sum(v == "stale" for v in verdicts),
            "down": sum(v == "down" for v in verdicts),
            "transitions": transitions,
            "queue_depths": depths,
            "queue_depth": sum(depths.values()),
            "goodput_total": goodput,
            "goodput_delta": goodput - prev_good
            if prev_good is not None else 0.0,
            "work_pending": work_pending,
            "tenants": tenants,
        }

    def _observe(self, row):
        """Ledger + detectors + anomaly accounting (the fleet-scope
        mirror of HealthMonitor.observe)."""
        self.ledger.append(row)
        fired = []
        for det in self.detectors:
            try:
                verdict = det.observe(row, self.ledger)
            except Exception:  # noqa: BLE001 - detectors can't be fatal
                self._c_detector_errors.labels(det.name).inc()
                continue
            if verdict:
                self._c_anomalies.labels(det.name).inc()
                args = {k: v for k, v in verdict.items()
                        if isinstance(v, (int, float, str, bool))}
                self._recorder.record(f"fleet/{det.name}",
                                      self._clock(), 0.0, args=args)
                with self._lock:
                    st = self._detector_state.setdefault(
                        det.name, {"fired": 0, "last_verdict": None})
                    st["fired"] += 1
                    st["last_verdict"] = dict(verdict)
                fired.append(verdict)
        return fired

    # ----------------------------------------------------- lifecycle
    def start(self):
        """Run the poll cycle on a daemon thread every ``interval_s``
        until :meth:`stop`. Idempotent."""
        with self._lock:
            if self._thread is not None:
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="fleet-poller")
            self._thread.start()
        return self

    def _loop(self):
        while not self._stop.is_set():
            t0 = self._clock()
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 - the loop must survive
                pass
            elapsed = self._clock() - t0
            self._stop.wait(max(0.0, self.interval_s - elapsed))

    def stop(self):
        """Stop the background cycle (idempotent); poll state is kept."""
        self._stop.set()
        with self._lock:
            thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=self.timeout_s * 4 + 5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    # ----------------------------------------------------- reporting
    def detector_counts(self):
        with self._lock:
            return {d.name: self._detector_state.get(
                d.name, {}).get("fired", 0) for d in self.detectors}

    def _health_block(self):
        counts = self.detector_counts()
        with self._lock:
            last = {n: dict(st["last_verdict"])
                    for n, st in self._detector_state.items()
                    if st.get("last_verdict")}
        return {
            "anomalies_total": sum(counts.values()),
            "detectors": counts,
            "last_verdicts": last,
        }

    def snapshot(self):
        """The pinned-schema ``FleetSnapshot`` (``/fleet/state``)."""
        now = self._clock()
        with self._lock:
            entries = [rollup.replica_entry(st, now)
                       for st in self.replicas]
            snapshots = [st.metrics for st in self.replicas
                         if st.metrics is not None]
            states = [st.state for st in self.replicas
                      if st.state is not None]
            polls = self._polls
        replicas = {}
        for e in entries:
            key = e["replica_id"]
            while key in replicas:       # colliding ids stay visible
                key += "+"
            replicas[key] = e
        return {
            "schema": rollup.FLEET_SCHEMA,
            "t": time.time(),
            "polls": polls,
            "interval_s": self.interval_s,
            "replicas": replicas,
            "fleet": rollup.fleet_aggregate(entries, snapshots,
                                            states),
            "health": self._health_block(),
        }

    def fleet_tenants(self):
        """The ``/fleet/tenants`` body: the federated per-tenant
        rollup (exact counter sums across replicas) plus the tenant
        detectors' firing state — the one-page noisy-neighbor view."""
        with self._lock:
            snapshots = [st.metrics for st in self.replicas
                         if st.metrics is not None]
            states = [st.state for st in self.replicas
                      if st.state is not None]
            polls = self._polls
        counts = self.detector_counts()
        with self._lock:
            last = {n: dict(st["last_verdict"])
                    for n, st in self._detector_state.items()
                    if st.get("last_verdict")
                    and n in ("noisy_neighbor", "tenant_starvation")}
        return {
            "polls": polls,
            "fleet": rollup.fleet_tenants(snapshots, states),
            "detectors": {n: counts.get(n, 0)
                          for n in ("noisy_neighbor",
                                    "tenant_starvation")
                          if n in counts},
            "last_verdicts": last,
        }

    def fleet_health(self):
        """The ``/fleet/health`` body — the router's one-poll answer:
        fleet-level healthy verdict, the availability census, each
        replica's posture, and the fleet-detector rollup."""
        snap = self.snapshot()
        fleet = snap["fleet"]
        return {
            "healthy": fleet["healthy"],
            "size": fleet["size"],
            "up": fleet["up"],
            "stale": fleet["stale"],
            "down": fleet["down"],
            "replicas": {
                rid: {k: e[k] for k in
                      ("verdict", "healthy", "degraded", "draining",
                       "restarts", "age_s")}
                for rid, e in snap["replicas"].items()},
            "anomalies_total": snap["health"]["anomalies_total"],
            "detectors": snap["health"]["detectors"],
            "polls": snap["polls"],
        }

    def prometheus_text(self):
        """The ``/fleet/metrics`` body: every non-down replica's
        last-known snapshot re-exposed as ONE Prometheus text
        exposition with a ``replica`` label stamped on every series —
        scrape-merge-time labeling, Prometheus-federation style."""
        with self._lock:
            labeled = [(st.replica_id, st.metrics)
                       for st in self.replicas
                       if st.verdict != "down"
                       and st.metrics is not None]
        return prometheus_text_from_snapshots(labeled)
