"""Replica identity: who IS this engine, in a fleet of lookalikes.

Every serving replica needs a stable, human-readable identity before
any fleet view can exist: scrape results must be attributable to one
process (two replicas on one host differ only by pid), incident
bundles collected off a fleet member must name which member, and a
rolling deploy needs the version visible per replica. The identity is

  * configured — ``ServingConfig(replica_id=...)`` or the
    ``PADDLE_REPLICA_ID`` env var (the k8s/pod-name case), else
  * derived — ``<hostname>:<pid>`` (:func:`default_replica_id`):
    stable for the process lifetime, unique across a host's replicas,
    and meaningful in logs without a lookup table.

The engine stamps it into ``snapshot()["replica"]``, ``/debug/state``,
``/debug/health`` and incident bundles, exposes
``serving_uptime_seconds`` (a restart-detection signal: uptime going
BACKWARDS between scrapes means the process bounced), and registers a
``paddle_tpu_build_info{replica, version, jax_version}`` info gauge
(value 1, Prometheus ``*_info`` convention) so ``/fleet/metrics`` can
tell replicas and versions apart without a side channel.
"""
import os
import socket
import time

__all__ = ["default_replica_id", "ReplicaIdentity"]


def default_replica_id():
    """A stable host:pid-derived replica id — unique per process on a
    host, stable for the process lifetime, readable in a log line."""
    try:
        host = socket.gethostname() or "localhost"
    except OSError:
        host = "localhost"
    return f"{host.split('.')[0]}:{os.getpid()}"


class ReplicaIdentity:
    """One replica's identity + uptime clock, shared by every surface
    that stamps it (snapshot / debug routes / incident bundles)."""

    def __init__(self, replica_id=None, clock=time.perf_counter):
        self.replica_id = str(replica_id) if replica_id \
            else default_replica_id()
        self._clock = clock
        self._t0 = clock()
        self.started_at = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())

    def uptime_s(self):
        return self._clock() - self._t0

    def report(self):
        """The ``snapshot()["replica"]`` / ``/debug/state["replica"]``
        body."""
        return {
            "replica_id": self.replica_id,
            "uptime_s": round(self.uptime_s(), 3),
            "started_at": self.started_at,
        }
