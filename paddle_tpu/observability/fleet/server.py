"""The fleet federation HTTP surface.

``FleetServer`` wraps a :class:`~.poller.FleetPoller` with the same
stdlib HTTP machinery every engine already uses
(``registry.start_metrics_server``) and mounts the three routes the
PR-12 router will consume:

  * ``/fleet/health`` — fleet-level healthy verdict + availability
    census + per-replica posture + fleet-detector rollup;
  * ``/fleet/state``  — the full pinned-schema ``FleetSnapshot``
    (per-replica entries, exact counter sums, bucket-wise merged
    latency percentiles);
  * ``/fleet/metrics`` — every non-down replica's metrics re-exposed
    as one Prometheus text exposition with a ``replica`` label on
    every series (scrape-merge-time labeling);
  * ``/fleet/tenants`` — the federated per-tenant attribution rollup
    plus the noisy_neighbor / tenant_starvation detector state.

``/metrics`` + ``/metrics.json`` serve the poller's OWN registry
(scrape outcomes, availability gauges, ``fleet_anomalies_total``) —
the observatory observes itself, same as every layer below it.
"""
from ..registry import start_metrics_server
from .poller import FleetPoller

__all__ = ["FleetServer"]


class FleetServer:
    """Own a poller + serve the fleet surface. ``poller`` may be a
    ready FleetPoller or a target list (poller kwargs pass through).
    ``serve()`` starts the poll loop and the HTTP server; ``close()``
    stops both (idempotent; also a context manager)."""

    def __init__(self, poller, **poller_kw):
        if not isinstance(poller, FleetPoller):
            poller = FleetPoller(poller, **poller_kw)
        elif poller_kw:
            raise TypeError("pass a FleetPoller OR targets + kwargs, "
                            "not both")
        self.poller = poller
        self.handle = None
        self._closed = False

    def routes(self):
        return {
            "/fleet/health": self.poller.fleet_health,
            "/fleet/state": self.poller.snapshot,
            "/fleet/metrics": self.poller.prometheus_text,
            "/fleet/tenants": self.poller.fleet_tenants,
        }

    def serve(self, port=0, addr="127.0.0.1", poll=True):
        """Start the HTTP surface (and, with ``poll=True``, the
        background poll loop). Returns the MetricsServerHandle —
        ``handle.port`` is the bound port."""
        if self.handle is not None:
            return self.handle
        if poll:
            self.poller.start()
        self.handle = start_metrics_server(
            self.poller.registry, port=port, addr=addr,
            extra_routes=self.routes())
        return self.handle

    @property
    def port(self):
        return self.handle.port if self.handle is not None else None

    def close(self):
        if self._closed:
            return
        self._closed = True
        self.poller.stop()
        if self.handle is not None:
            self.handle.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
