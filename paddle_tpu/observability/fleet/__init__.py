"""Fleet observatory: replica identity, resilient multi-replica
scraping, and federated fleet-level health/perf/SLO rollups.

PRs 8-10 made ONE engine replica fully observable (``/debug/health``,
``/debug/ledger``, ``/debug/perf``) — but only one replica at a time,
read by a human. This package is the federation layer over N of them:
the sensory system the ROADMAP direction-#2 router process will stand
on.

  * **identity** — every engine carries a stable replica id
    (``ServingConfig(replica_id=)`` / ``$PADDLE_REPLICA_ID`` /
    host:pid), ``serving_uptime_seconds``, and a
    ``paddle_tpu_build_info`` info gauge, stamped into its snapshot,
    debug routes and incident bundles — fleet views tell replicas and
    versions apart, and a bundle collected off one replica is
    attributable after the fact;
  * **poller.FleetPoller** — scrapes a static replica list
    (``host:port`` / JSON registry file) on an interval with
    per-replica timeout, exponential backoff, ``last_seen`` staleness
    marking, and consecutive-failure eviction / readmission verdicts
    (``up | stale | down``) — the health-poll replica lifecycle the
    router spec calls for;
  * **rollup** — the pinned-schema ``FleetSnapshot``: per-replica
    posture plus fleet aggregates that merge EXACTLY (counters sum;
    the fixed-bucket histograms merge bucket-wise, so fleet TTFT /
    latency percentiles come from the merged distribution, never
    averaged percentiles), judged by ``scope="fleet"`` detectors
    (``replica_flap`` / ``fleet_goodput_collapse`` / ``load_skew`` /
    ``noisy_neighbor`` / ``tenant_starvation``) in the PR-8
    ``register_detector`` framework;
  * **server.FleetServer** — ``/fleet/health``, ``/fleet/state``,
    ``/fleet/metrics`` (Prometheus text with a ``replica`` label on
    every series), ``/fleet/tenants`` (the federated per-tenant
    attribution rollup + fairness-detector state).

``tools/fleet_top.py`` renders the fleet table from the same poller
(one-shot or ``--watch``), exiting 0 iff every replica is up and
healthy.
"""
from . import detectors as _fleet_detectors  # noqa: F401 - registers
from .identity import ReplicaIdentity, default_replica_id  # noqa: F401
from .poller import (  # noqa: F401
    FLEET_ROW_KEYS, FLEET_TENANT_ROW_KEYS, FleetPoller, ReplicaState,
)
from .rollup import (  # noqa: F401
    FLEET_AGG_KEYS, FLEET_REPLICA_KEYS, FLEET_SCHEMA,
    FLEET_SNAPSHOT_KEYS, FLEET_TENANT_ENTRY_KEYS, fleet_aggregate,
    fleet_cache, fleet_tenants, merged_latency, replica_entry,
)
from .server import FleetServer  # noqa: F401
