"""Fleet-level anomaly detectors over the poller's rollup rows.

Same framework as the PR-8 engine detectors
(``health.detectors.register_detector``), different scope and row
vocabulary: these are registered under ``scope="fleet"`` and evaluate
ONE row per completed poll cycle (see ``poller.FleetPoller._fleet_row``
— ``step`` is the poll sequence number, so the shared ``Detector``
base and ledger machinery apply unchanged). Engine detectors judge
one replica's steps; these judge the fleet's SHAPE:

``replica_flap``
    a replica's availability verdict changed — ``up``→``down`` (the
    router must stop dispatching there NOW) or ``down``→``up``
    (readmission; rapid alternation is the classic flapping replica a
    human should look at). Fires once per transition, naming the
    replicas and directions.
``fleet_goodput_collapse``
    the FLEET's SLO-met tokens/sec falling off a cliff between
    adjacent poll windows while work is pending — the every-replica-
    degraded-at-once signature (shared dependency died, overload
    breached every replica simultaneously) that no single replica's
    own goodput_collapse detector can distinguish from its neighbors'.
``load_skew``
    sustained queue-depth imbalance across UP replicas (max far above
    the fleet mean while the mean shows real load): the
    dispatch-layer-is-broken signature — one replica drowning while
    its peers idle means routing, not capacity, is the problem.
``noisy_neighbor``
    one tenant dominating the fleet's generated tokens over a poll
    window WHILE the other tenants' SLO attainment over the same
    window is poor — capacity is being monopolized at the victims'
    expense. Judged from the per-tenant deltas in ``row["tenants"]``
    (exact fleet counter sums differenced between cycles).
``tenant_starvation``
    a tenant with work QUEUED somewhere gets zero admissions for
    ``sustain`` consecutive polls while OTHER tenants keep getting
    admitted — the fairness inverse of noisy_neighbor: not slow
    service, no service.
"""
import collections

from ..health.detectors import Detector, register_detector

__all__ = ["ReplicaFlap", "FleetGoodputCollapse", "LoadSkew",
           "NoisyNeighbor", "TenantStarvation"]


@register_detector("replica_flap", scope="fleet")
class ReplicaFlap(Detector):
    """Fires on any availability transition involving ``down``:
    ``up/stale``→``down`` (lost) and ``down``→``up`` (readmitted).
    Transitions appear in exactly one poll row, so each change fires
    exactly once."""

    def observe(self, row, ledger):
        flaps = [t for t in row.get("transitions", ())
                 if t["to"] == "down" or t["from"] == "down"]
        if not flaps:
            return None
        names = ", ".join(f"{t['replica']}:{t['from']}->{t['to']}"
                          for t in flaps)
        return self._verdict(
            row, f"replica availability changed: {names}",
            replicas=[t["replica"] for t in flaps],
            transitions=[dict(t) for t in flaps],
            down=int(row.get("down", 0)))


@register_detector("fleet_goodput_collapse", scope="fleet")
class FleetGoodputCollapse(Detector):
    """Fleet-aggregate SLO-met tokens/sec cliff between adjacent
    ``window``-poll windows: previous window healthy (>=
    ``healthy_frac`` of the best windowed rate seen), current window
    below ``drop_frac`` of it, work still pending somewhere in the
    fleet. Inert while no replica reports goodput (no SLO targets
    configured fleet-wide)."""

    def __init__(self, window=8, drop_frac=0.1, healthy_frac=0.5):
        self.window = int(window)
        self.drop_frac = float(drop_frac)
        self.healthy_frac = float(healthy_frac)
        self._rows = collections.deque(maxlen=2 * self.window)
        self._peak = 0.0

    @staticmethod
    def _rate(seg):
        dt = sum(d for _, d in seg)
        good = sum(g for g, _ in seg)
        return good / dt if dt > 0 else 0.0

    def observe(self, row, ledger):
        self._rows.append((float(row.get("goodput_delta", 0.0)),
                           float(row.get("dt_s", 0.0))))
        if len(self._rows) < 2 * self.window:
            return None
        rows = list(self._rows)
        prev = self._rate(rows[:self.window])
        cur = self._rate(rows[self.window:])
        if prev > 0:
            self._peak = max(self._peak, prev)
        if (row.get("work_pending")
                and self._peak > 0
                and prev >= self.healthy_frac * self._peak
                and cur < self.drop_frac * prev):
            self._rows.clear()
            return self._verdict(
                row,
                f"fleet goodput {cur:.1f} tok/s collapsed from "
                f"{prev:.1f} tok/s",
                window_polls=self.window,
                previous_rate_tps=round(prev, 3),
                current_rate_tps=round(cur, 3),
                peak_rate_tps=round(self._peak, 3))
        return None


@register_detector("load_skew", scope="fleet")
class LoadSkew(Detector):
    """Queue-depth imbalance across UP replicas, sustained for
    ``sustain`` consecutive polls: the worst replica holds >=
    ``min_depth`` queued requests AND >= ``skew_factor`` x (its
    PEERS' mean depth + 1). Judging the worst against its peers (not
    the fleet mean, which the worst itself dominates on small fleets
    — with N replicas max/mean is bounded by N) makes the
    one-replica-drowning-while-peers-idle signature detectable at any
    fleet size >= ``min_replicas``. The absolute ``min_depth`` floor
    keeps an idle fleet's zero-vs-one jitter quiet. Fires once per
    episode; re-arms when balance returns."""

    def __init__(self, skew_factor=4.0, min_depth=6, sustain=3,
                 min_replicas=2):
        self.skew_factor = float(skew_factor)
        self.min_depth = int(min_depth)
        self.sustain = int(sustain)
        self.min_replicas = int(min_replicas)
        self._streak = 0
        self._fired = False

    def observe(self, row, ledger):
        depths = row.get("queue_depths") or {}
        if len(depths) < self.min_replicas:
            self._streak = 0
            self._fired = False
            return None
        worst = max(depths, key=lambda r: depths[r])
        peers = [v for r, v in depths.items() if r != worst]
        peer_mean = sum(peers) / len(peers)
        skewed = (depths[worst] >= self.min_depth
                  and depths[worst]
                  >= self.skew_factor * (peer_mean + 1.0))
        if not skewed:
            self._streak = 0
            self._fired = False
            return None
        self._streak += 1
        if self._streak >= self.sustain and not self._fired:
            self._fired = True
            return self._verdict(
                row,
                f"queue skew: {worst} holds {depths[worst]} queued vs "
                f"peer mean {peer_mean:.1f}",
                replica=worst,
                max_queue_depth=int(depths[worst]),
                peer_mean_queue_depth=round(peer_mean, 2),
                polls_skewed=self._streak)
        return None


@register_detector("noisy_neighbor", scope="fleet")
class NoisyNeighbor(Detector):
    """One tenant's generated-token share over the last ``window``
    polls >= ``share_frac`` of the fleet total WHILE the OTHER
    tenants' SLO attainment over the same window (their summed
    attained / summed completions+violations) is below
    ``attain_floor``. Both halves must hold: a tenant dominating an
    otherwise-healthy fleet is just the biggest customer, and poor
    fleet-wide attainment without a dominant tenant is overload, not
    a neighbor problem. Volume gates (``min_tokens`` window tokens,
    ``min_victim_judged`` victim verdicts) keep idle/cold windows
    quiet. Fires once per episode; re-arms when either half clears."""

    def __init__(self, window=8, share_frac=0.6, attain_floor=0.5,
                 min_tokens=100, min_victim_judged=3):
        self.window = int(window)
        self.share_frac = float(share_frac)
        self.attain_floor = float(attain_floor)
        self.min_tokens = float(min_tokens)
        self.min_victim_judged = float(min_victim_judged)
        self._rows = collections.deque(maxlen=self.window)
        self._fired = False

    def observe(self, row, ledger):
        self._rows.append(row.get("tenants") or {})
        if len(self._rows) < self.window:
            return None
        tokens, attained, judged = {}, {}, {}
        for facts in self._rows:
            for t, f in facts.items():
                tokens[t] = tokens.get(t, 0.0) \
                    + (f.get("tokens_delta") or 0.0)
                att = f.get("attained_delta") or 0.0
                attained[t] = attained.get(t, 0.0) + att
                judged[t] = judged.get(t, 0.0) + att \
                    + (f.get("violated_delta") or 0.0)
        total = sum(tokens.values())
        if total < self.min_tokens or len(tokens) < 2:
            self._fired = False
            return None
        top = max(tokens, key=lambda t: (tokens[t], t))
        share = tokens[top] / total
        victim_judged = sum(v for t, v in judged.items() if t != top)
        if victim_judged < self.min_victim_judged:
            self._fired = False
            return None
        victim_attain = sum(
            v for t, v in attained.items() if t != top) / victim_judged
        noisy = (share >= self.share_frac
                 and victim_attain < self.attain_floor)
        if not noisy:
            self._fired = False
            return None
        if self._fired:
            return None
        self._fired = True
        return self._verdict(
            row,
            f"tenant {top} holds {share:.0%} of fleet tokens over "
            f"{self.window} polls while other tenants attain "
            f"{victim_attain:.0%}",
            tenant=top,
            token_share=round(share, 4),
            victim_attainment=round(victim_attain, 4),
            window_polls=self.window,
            window_tokens=round(total, 1))


@register_detector("tenant_starvation", scope="fleet")
class TenantStarvation(Detector):
    """A tenant with queued work admitted NOWHERE for ``sustain``
    consecutive polls while other tenants' admissions kept flowing.
    Per-tenant streaks (several tenants can starve at once, each
    fires on its own schedule); a poll with zero fleet-wide
    admissions resets nothing — an idle or wedged fleet is a
    different detector's problem, starvation is specifically unfair
    SHARING of admissions that are happening."""

    def __init__(self, sustain=3, min_queued=1):
        self.sustain = int(sustain)
        self.min_queued = int(min_queued)
        self._streaks = {}
        self._fired = set()

    def observe(self, row, ledger):
        facts = row.get("tenants") or {}
        total_adm = sum((f.get("requests_delta") or 0.0)
                        for f in facts.values())
        for t in list(self._streaks):
            if t not in facts:
                self._streaks.pop(t, None)
                self._fired.discard(t)
        for t, f in sorted(facts.items()):
            own_adm = f.get("requests_delta") or 0.0
            queued = f.get("queued") or 0
            if own_adm > 0 or queued < self.min_queued:
                self._streaks.pop(t, None)
                self._fired.discard(t)
                continue
            if total_adm - own_adm <= 0:
                # nobody got admitted: the fleet is idle/wedged, not
                # unfair — hold the streak, don't grow it
                continue
            streak = self._streaks.get(t, 0) + 1
            self._streaks[t] = streak
            if streak >= self.sustain and t not in self._fired:
                self._fired.add(t)
                return self._verdict(
                    row,
                    f"tenant {t} starved: {queued} queued, zero "
                    f"admissions for {streak} polls while peers "
                    f"admitted {total_adm:.0f}",
                    tenant=t,
                    queued=int(queued),
                    polls_starved=streak,
                    peer_admissions=round(total_adm, 1))
        return None
