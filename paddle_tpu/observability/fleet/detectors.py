"""Fleet-level anomaly detectors over the poller's rollup rows.

Same framework as the PR-8 engine detectors
(``health.detectors.register_detector``), different scope and row
vocabulary: these are registered under ``scope="fleet"`` and evaluate
ONE row per completed poll cycle (see ``poller.FleetPoller._fleet_row``
— ``step`` is the poll sequence number, so the shared ``Detector``
base and ledger machinery apply unchanged). Engine detectors judge
one replica's steps; these judge the fleet's SHAPE:

``replica_flap``
    a replica's availability verdict changed — ``up``→``down`` (the
    router must stop dispatching there NOW) or ``down``→``up``
    (readmission; rapid alternation is the classic flapping replica a
    human should look at). Fires once per transition, naming the
    replicas and directions.
``fleet_goodput_collapse``
    the FLEET's SLO-met tokens/sec falling off a cliff between
    adjacent poll windows while work is pending — the every-replica-
    degraded-at-once signature (shared dependency died, overload
    breached every replica simultaneously) that no single replica's
    own goodput_collapse detector can distinguish from its neighbors'.
``load_skew``
    sustained queue-depth imbalance across UP replicas (max far above
    the fleet mean while the mean shows real load): the
    dispatch-layer-is-broken signature — one replica drowning while
    its peers idle means routing, not capacity, is the problem.
"""
import collections

from ..health.detectors import Detector, register_detector

__all__ = ["ReplicaFlap", "FleetGoodputCollapse", "LoadSkew"]


@register_detector("replica_flap", scope="fleet")
class ReplicaFlap(Detector):
    """Fires on any availability transition involving ``down``:
    ``up/stale``→``down`` (lost) and ``down``→``up`` (readmitted).
    Transitions appear in exactly one poll row, so each change fires
    exactly once."""

    def observe(self, row, ledger):
        flaps = [t for t in row.get("transitions", ())
                 if t["to"] == "down" or t["from"] == "down"]
        if not flaps:
            return None
        names = ", ".join(f"{t['replica']}:{t['from']}->{t['to']}"
                          for t in flaps)
        return self._verdict(
            row, f"replica availability changed: {names}",
            replicas=[t["replica"] for t in flaps],
            transitions=[dict(t) for t in flaps],
            down=int(row.get("down", 0)))


@register_detector("fleet_goodput_collapse", scope="fleet")
class FleetGoodputCollapse(Detector):
    """Fleet-aggregate SLO-met tokens/sec cliff between adjacent
    ``window``-poll windows: previous window healthy (>=
    ``healthy_frac`` of the best windowed rate seen), current window
    below ``drop_frac`` of it, work still pending somewhere in the
    fleet. Inert while no replica reports goodput (no SLO targets
    configured fleet-wide)."""

    def __init__(self, window=8, drop_frac=0.1, healthy_frac=0.5):
        self.window = int(window)
        self.drop_frac = float(drop_frac)
        self.healthy_frac = float(healthy_frac)
        self._rows = collections.deque(maxlen=2 * self.window)
        self._peak = 0.0

    @staticmethod
    def _rate(seg):
        dt = sum(d for _, d in seg)
        good = sum(g for g, _ in seg)
        return good / dt if dt > 0 else 0.0

    def observe(self, row, ledger):
        self._rows.append((float(row.get("goodput_delta", 0.0)),
                           float(row.get("dt_s", 0.0))))
        if len(self._rows) < 2 * self.window:
            return None
        rows = list(self._rows)
        prev = self._rate(rows[:self.window])
        cur = self._rate(rows[self.window:])
        if prev > 0:
            self._peak = max(self._peak, prev)
        if (row.get("work_pending")
                and self._peak > 0
                and prev >= self.healthy_frac * self._peak
                and cur < self.drop_frac * prev):
            self._rows.clear()
            return self._verdict(
                row,
                f"fleet goodput {cur:.1f} tok/s collapsed from "
                f"{prev:.1f} tok/s",
                window_polls=self.window,
                previous_rate_tps=round(prev, 3),
                current_rate_tps=round(cur, 3),
                peak_rate_tps=round(self._peak, 3))
        return None


@register_detector("load_skew", scope="fleet")
class LoadSkew(Detector):
    """Queue-depth imbalance across UP replicas, sustained for
    ``sustain`` consecutive polls: the worst replica holds >=
    ``min_depth`` queued requests AND >= ``skew_factor`` x (its
    PEERS' mean depth + 1). Judging the worst against its peers (not
    the fleet mean, which the worst itself dominates on small fleets
    — with N replicas max/mean is bounded by N) makes the
    one-replica-drowning-while-peers-idle signature detectable at any
    fleet size >= ``min_replicas``. The absolute ``min_depth`` floor
    keeps an idle fleet's zero-vs-one jitter quiet. Fires once per
    episode; re-arms when balance returns."""

    def __init__(self, skew_factor=4.0, min_depth=6, sustain=3,
                 min_replicas=2):
        self.skew_factor = float(skew_factor)
        self.min_depth = int(min_depth)
        self.sustain = int(sustain)
        self.min_replicas = int(min_replicas)
        self._streak = 0
        self._fired = False

    def observe(self, row, ledger):
        depths = row.get("queue_depths") or {}
        if len(depths) < self.min_replicas:
            self._streak = 0
            self._fired = False
            return None
        worst = max(depths, key=lambda r: depths[r])
        peers = [v for r, v in depths.items() if r != worst]
        peer_mean = sum(peers) / len(peers)
        skewed = (depths[worst] >= self.min_depth
                  and depths[worst]
                  >= self.skew_factor * (peer_mean + 1.0))
        if not skewed:
            self._streak = 0
            self._fired = False
            return None
        self._streak += 1
        if self._streak >= self.sustain and not self._fired:
            self._fired = True
            return self._verdict(
                row,
                f"queue skew: {worst} holds {depths[worst]} queued vs "
                f"peer mean {peer_mean:.1f}",
                replica=worst,
                max_queue_depth=int(depths[worst]),
                peer_mean_queue_depth=round(peer_mean, 2),
                polls_skewed=self._streak)
        return None
