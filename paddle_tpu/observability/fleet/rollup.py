"""Federated fleet rollups: many replica scrapes, one pinned snapshot.

The poller hands this module each replica's last-known scrape bodies
(``/metrics.json`` snapshot, ``/debug/health``, ``/debug/state``) plus
its own availability bookkeeping; this module folds them into the
``FleetSnapshot`` — the ``/fleet/state`` body and the surface the
PR-12 router will consume. Two merge rules, applied EXACTLY:

  * **counters sum** — tokens, goodput, completions are additive
    facts; the fleet total is the sum over replicas' last-known
    cumulative counters (down replicas keep contributing their last
    observed totals: a crashed replica's already-served tokens
    happened);
  * **histograms merge bucket-wise** — fleet TTFT / request-latency
    percentiles come from ``registry.merge_histogram_snapshots`` over
    the per-replica fixed-bucket histograms and
    ``registry.percentile_from_buckets`` over the MERGED distribution.
    Averaging per-replica percentiles is statistically meaningless
    (a p99 of averages is not an average of p99s); merged buckets are
    the one representation that aggregates exactly.

``FLEET_SNAPSHOT_KEYS`` / ``FLEET_REPLICA_KEYS`` / ``FLEET_AGG_KEYS``
are the schema contract (tests/test_fleet.py pins them — keys only
get added, never renamed).
"""
from ..cache import merge_heat_digests, merge_mrc_points
from ..registry import merge_histogram_snapshots, percentile_from_buckets

FLEET_SCHEMA = "paddle_tpu.fleet/v1"

# /fleet/state top level
FLEET_SNAPSHOT_KEYS = (
    "schema", "t", "polls", "interval_s", "replicas", "fleet",
    "health",
)

# one entry per replica (identity + availability + posture + load)
FLEET_REPLICA_KEYS = (
    "replica_id",     # self-reported id (configured id until learned)
    "url",            # scrape base URL
    "verdict",        # up | stale | down (availability)
    "healthy",        # the replica's own /debug/health verdict
    "degraded",       # supervisor-restart replay still draining
    "draining",       # graceful drain in progress
    "restarts",       # cumulative supervisor restarts
    "queue_depth",    # queued requests at last scrape
    "occupancy",      # live slots / num_slots at last scrape
    "steps",          # engine steps ever (health ledger)
    "step_rate",      # steps/sec between the last two scrapes
    "tokens_generated",
    "goodput_tokens",
    "requests_completed",
    "roofline_fraction",   # decode program, when priced
    "cache_hit_rate",      # block-granular prefix-cache hit rate
    "cache_saved_ttft_ms",  # estimated TTFT ms saved by cache hits
    "cache_thrash",        # evict-then-reinsert events (cumulative)
    "uptime_s",       # replica-reported process uptime
    "version",        # paddle_tpu_build_info version label
    "age_s",          # seconds since the last successful scrape
    "consecutive_failures",
    "polls",          # scrape attempts against this replica
    "failures",       # of those, failed
    "evictions",      # up->down verdict flips
    "readmissions",   # down->up verdict flips
    "scrape_ms",      # last successful scrape round-trip
    "last_error",     # last scrape failure, abbreviated (None when up)
)

# the fleet-level aggregate block
FLEET_AGG_KEYS = (
    "size", "up", "stale", "down", "healthy", "queue_depth",
    "occupancy", "step_rate", "tokens_generated", "goodput_tokens",
    "requests_completed", "latency", "roofline_fraction", "cache",
    "tenants",
)

# per-tenant fleet rollup: (entry key, tenant-labelled counter family)
# — every one an additive fact, so the fleet row is the exact sum of
# per-replica series (never a mean of per-replica rates)
_TENANT_COUNTERS = (
    ("requests", "serving_tenant_requests_total"),
    ("completed", "serving_tenant_completed_total"),
    ("tokens_in", "serving_tenant_tokens_in_total"),
    ("tokens_out", "serving_tenant_tokens_out_total"),
    ("goodput_tokens", "serving_tenant_goodput_tokens_total"),
    ("attained", "serving_tenant_slo_attained_total"),
    ("violations", "serving_tenant_slo_violations_total"),
    ("shed", "serving_tenant_shed_total"),
    ("cache_saved_tokens", "serving_tenant_cache_saved_tokens_total"),
)

FLEET_TENANT_ENTRY_KEYS = tuple(k for k, _ in _TENANT_COUNTERS) + (
    "queued", "attainment", "token_share",
)

_PCTS = ((50, "p50_ms"), (90, "p90_ms"), (99, "p99_ms"))
_LATENCY_FAMILIES = (("ttft", "serving_ttft_seconds"),
                     ("request_latency",
                      "serving_request_latency_seconds"))


def counter_value(snap, name, labels=""):
    """One series' value out of a registry ``snapshot()`` dict; None
    when the family or series is absent (an older replica, or a
    family that never accrued)."""
    fam = (snap or {}).get(name)
    if not fam:
        return None
    v = fam.get("values", {}).get(labels)
    return v if isinstance(v, (int, float)) else None


def histogram_value(snap, name, labels=""):
    """One histogram series ({count, sum, buckets}) or None."""
    fam = (snap or {}).get(name)
    if not fam or fam.get("type") != "histogram":
        return None
    v = fam.get("values", {}).get(labels)
    return v if isinstance(v, dict) else None


def build_info_labels(snap):
    """The first ``paddle_tpu_build_info`` series' labels as a dict
    (replica / version / jax_version), {} when absent."""
    fam = (snap or {}).get("paddle_tpu_build_info")
    for key in (fam or {}).get("values", {}):
        out = {}
        for part in key.split(","):
            k, _, v = part.partition("=")
            out[k] = v
        return out
    return {}


def _sum_known(values):
    known = [v for v in values if v is not None]
    return round(sum(known), 3) if known else None


def _mean_known(values):
    known = [v for v in values if v is not None]
    return round(sum(known) / len(known), 4) if known else None


def merged_latency(snapshots):
    """{"ttft": {count, p50_ms, p90_ms, p99_ms}, "request_latency":
    {...}} from bucket-wise merged per-replica histograms."""
    out = {}
    for name, family in _LATENCY_FAMILIES:
        entries = [histogram_value(s, family) for s in snapshots]
        merged = merge_histogram_snapshots(entries)
        entry = {"count": merged["count"]}
        for q, key in _PCTS:
            p = percentile_from_buckets(merged["buckets"], q)
            entry[key] = None if p is None else round(p * 1000.0, 3)
        out[name] = entry
    return out


def replica_entry(st, now):
    """One ``FLEET_REPLICA_KEYS`` row from a poller ReplicaState."""
    snap, health, state = st.metrics, st.health, st.state
    hrep = health or {}
    srep = state or {}
    replica_sec = srep.get("replica") or {}
    info = build_info_labels(snap)
    roofline = counter_value(snap, "serving_roofline_fraction",
                             "program=decode")
    c_hits = counter_value(snap, "serving_cache_block_hits_total")
    c_accesses = counter_value(snap,
                               "serving_cache_block_accesses_total")
    c_saved_ms = counter_value(snap,
                               "serving_cache_saved_ttft_ms_total")
    c_thrash = counter_value(snap,
                             "serving_cache_thrash_reinserts_total")
    return {
        "replica_id": st.replica_id,
        "url": st.url,
        "verdict": st.verdict,
        "healthy": hrep.get("healthy"),
        "degraded": hrep.get("degraded"),
        "draining": hrep.get("draining"),
        "restarts": hrep.get("restarts"),
        "queue_depth": srep.get("queue_depth"),
        "occupancy": srep.get("slot_occupancy"),
        "steps": (hrep.get("ledger") or {}).get("steps"),
        "step_rate": round(st.step_rate, 2)
        if st.step_rate is not None else None,
        "tokens_generated": counter_value(
            snap, "serving_tokens_generated_total"),
        "goodput_tokens": counter_value(
            snap, "serving_goodput_tokens_total"),
        "requests_completed": counter_value(
            snap, "serving_requests_completed_total"),
        "roofline_fraction": round(roofline, 6)
        if roofline else None,
        "cache_hit_rate": round((c_hits or 0.0) / c_accesses, 4)
        if c_accesses else None,
        "cache_saved_ttft_ms": round(c_saved_ms, 3)
        if c_saved_ms is not None else None,
        "cache_thrash": int(c_thrash) if c_thrash is not None
        else None,
        "uptime_s": replica_sec.get("uptime_s"),
        "version": info.get("version"),
        "age_s": round(now - st.last_seen, 3)
        if st.last_seen is not None else None,
        "consecutive_failures": st.consecutive_failures,
        "polls": st.polls,
        "failures": st.failures,
        "evictions": st.evictions,
        "readmissions": st.readmissions,
        "scrape_ms": round(st.scrape_s * 1000.0, 3)
        if st.scrape_s is not None else None,
        "last_error": st.last_error,
    }


def fleet_cache(snapshots, states):
    """The fleet-level ``cache`` block: counters sum exactly (hits /
    accesses summed BEFORE dividing — the fleet hit rate is the true
    pooled rate, not a mean of per-replica rates), the MRC merges as
    the sampled-access-weighted mean per capacity (algebraically the
    pooled-histogram estimate), and the heat digest merges by stable
    fingerprint then re-ranks. ``states`` are the replicas' last-known
    ``/debug/state`` bodies (the MRC curve and heat digest live
    there). None when no replica reports a cache section."""
    accesses = _sum_known([counter_value(
        s, "serving_cache_block_accesses_total") for s in snapshots])
    if accesses is None:
        return None
    hits = _sum_known([counter_value(
        s, "serving_cache_block_hits_total") for s in snapshots])
    point_lists, weights, digests = [], [], []
    for state in states:
        cache = (state or {}).get("cache") or {}
        if not cache.get("enabled"):
            continue
        if cache.get("mrc"):
            point_lists.append(cache["mrc"])
            weights.append(
                (cache.get("sampled") or {}).get("accesses") or 0)
        top = (cache.get("heat") or {}).get("top")
        if top:
            digests.append(top)
    return {
        "accesses": accesses,
        "hits": hits,
        "hit_rate": round((hits or 0.0) / accesses, 4)
        if accesses else None,
        "saved_tokens": _sum_known([counter_value(
            s, "serving_cache_saved_tokens_total")
            for s in snapshots]),
        "saved_ttft_ms": _sum_known([counter_value(
            s, "serving_cache_saved_ttft_ms_total")
            for s in snapshots]),
        "thrash_reinserts": _sum_known([counter_value(
            s, "serving_cache_thrash_reinserts_total")
            for s in snapshots]),
        "mrc": merge_mrc_points(point_lists, weights)
        if point_lists else None,
        "heat_top": merge_heat_digests(digests) if digests else None,
    }


def fleet_tenants(snapshots, states):
    """The fleet-level ``tenants`` block: every per-tenant counter
    sums exactly across replicas (same merge rule as every other
    fleet counter), queue depths sum from the replicas' last-known
    ``/debug/state`` tenant sections, and the derived rates —
    ``attainment`` (attained / requests) and ``token_share``
    (tokens_out / fleet tokens_out) — divide the SUMS, never average
    per-replica ratios. None when no replica exposes tenant series
    (an all-disabled or pre-tenant fleet)."""
    rows = {}
    seen = False

    def _row(t):
        return rows.setdefault(
            t, dict({k: 0 for k, _ in _TENANT_COUNTERS}, queued=0))

    for snap in snapshots:
        for key, family in _TENANT_COUNTERS:
            fam = (snap or {}).get(family)
            if not fam:
                continue
            seen = True
            for labels, v in (fam.get("values") or {}).items():
                if not labels.startswith("tenant=") \
                        or not isinstance(v, (int, float)):
                    continue
                _row(labels[len("tenant="):])[key] += v
    folded = 0
    for state in states:
        sec = (state or {}).get("tenants") or {}
        if not sec.get("enabled"):
            continue
        seen = True
        folded += (sec.get("overflow") or {}).get("folded_events") or 0
        for t, entry in (sec.get("tenants") or {}).items():
            _row(t)["queued"] += entry.get("queued") or 0
    if not seen:
        return None
    total_out = sum(r["tokens_out"] for r in rows.values())
    for row in rows.values():
        row["attainment"] = round(
            row["attained"] / row["requests"], 4) \
            if row["requests"] else None
        row["token_share"] = round(
            row["tokens_out"] / total_out, 4) if total_out else None
    ordered = dict(sorted(rows.items(),
                          key=lambda kv: (-kv[1]["tokens_out"],
                                          kv[0])))
    return {
        "tenant_count": len(ordered),
        "overflow_folded": folded,
        "tenants": ordered,
    }


def fleet_aggregate(entries, snapshots, states=()):
    """The ``FLEET_AGG_KEYS`` block: availability census + exact
    counter sums + bucket-wise merged latency percentiles. ``entries``
    are the per-replica rows; ``snapshots`` the last-known metrics
    snapshots of every replica that ever scraped (down replicas'
    already-served work still counts); ``states`` the last-known
    ``/debug/state`` bodies (the cache MRC/heat merge sources)."""
    verdicts = [e["verdict"] for e in entries]
    up = sum(v == "up" for v in verdicts)
    stale = sum(v == "stale" for v in verdicts)
    down = len(verdicts) - up - stale
    healthy = bool(entries) and all(
        e["verdict"] == "up" and e["healthy"] is True
        and not e["degraded"] and not e["draining"] for e in entries)
    live = [e for e in entries if e["verdict"] != "down"]
    return {
        "size": len(entries),
        "up": up,
        "stale": stale,
        "down": down,
        "healthy": healthy,
        "queue_depth": _sum_known([e["queue_depth"] for e in live]),
        "occupancy": _mean_known([e["occupancy"] for e in live]),
        "step_rate": _sum_known([e["step_rate"] for e in live]),
        "tokens_generated": _sum_known(
            [e["tokens_generated"] for e in entries]),
        "goodput_tokens": _sum_known(
            [e["goodput_tokens"] for e in entries]),
        "requests_completed": _sum_known(
            [e["requests_completed"] for e in entries]),
        "latency": merged_latency(snapshots),
        "roofline_fraction": _mean_known(
            [e["roofline_fraction"] for e in live]),
        "cache": fleet_cache(snapshots, states),
        "tenants": fleet_tenants(snapshots, states),
    }
