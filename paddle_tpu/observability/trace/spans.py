"""Per-process trace spans: named, wall-anchored, bounded.

Each process in a request's path (router, prefill replica, decode
replica) owns one :class:`TraceRecorder` — a thread-safe bounded ring
of :class:`TraceSpan` records. Spans are WALL-anchored (epoch seconds
from ``time.time()``), unlike the flight recorder's perf_counter
timestamps: cross-process assembly needs a clock every replica
shares, and the assembler's offset estimation corrects what it
doesn't. Callers holding perf_counter stamps (the engine's existing
``Request`` lifecycle timestamps) convert through :meth:`wall`,
which anchors one perf_counter origin to one wall reading at recorder
construction — monotone within the process, drift-free at serving
time scales.

The nine CANONICAL_SEGMENTS are the TTFT critical path of a two-hop
disaggregated request; extra span names (``router/retry``,
``router/hedge``, ``router/failover``, ``router/request``) annotate
the retry machinery without entering the decomposition.

``/debug/traces`` serves :meth:`debug_traces` (spans + the replica's
wall clock at render time — the fact the assembler's skew bound needs);
``snapshot()["trace"]`` serves :meth:`snapshot` (TRACE_SNAPSHOT_KEYS
pinned, identical shape disabled).
"""
import collections
import os
import threading
import time

__all__ = ["CANONICAL_SEGMENTS", "TRACE_SNAPSHOT_KEYS", "TraceSpan",
           "TraceRecorder"]

# the TTFT critical path of a two-hop disaggregated request, in
# causal order; the assembler's completeness check and the bench's
# ttft_breakdown both key on exactly these names
CANONICAL_SEGMENTS = (
    "router/queue", "router/dispatch", "prefill/queue",
    "prefill/compute", "kv/export", "kv/wire", "kv/import",
    "decode/queue", "decode/first_step",
)

# snapshot()["trace"] schema (pinned in tests/test_observability.py)
TRACE_SNAPSHOT_KEYS = ("enabled", "spans_recorded", "spans_dropped",
                       "ring_occupancy", "ring_capacity")


class TraceSpan:
    """One named span of one trace on one replica. ``t0``/``dur`` are
    wall seconds (epoch); ``parent_id`` is the propagated caller span
    (the router's root for every per-hop span)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "replica",
                 "t0", "dur", "attrs")

    def __init__(self, trace_id, span_id, parent_id, name, replica,
                 t0, dur, attrs):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.replica = replica
        self.t0 = t0
        self.dur = dur
        self.attrs = attrs

    def as_dict(self):
        d = {"trace_id": self.trace_id, "span_id": self.span_id,
             "parent_id": self.parent_id, "name": self.name,
             "replica": self.replica, "t0": round(self.t0, 6),
             "dur": round(self.dur, 6)}
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d


class _SpanTimer:
    """Context manager handle from :meth:`TraceRecorder.span`."""

    __slots__ = ("_rec", "_ctx", "_name", "_attrs", "_t0")

    def __init__(self, rec, ctx, name, attrs):
        self._rec = rec
        self._ctx = ctx
        self._name = name
        self._attrs = attrs

    def __enter__(self):
        self._t0 = time.time()
        return self

    def __exit__(self, *exc):
        self._rec.record(self._ctx, self._name, self._t0,
                         time.time() - self._t0, self._attrs)
        return False


class TraceRecorder:
    """Thread-safe bounded ring of wall-anchored trace spans.

    ``enabled=False`` keeps the full surface (``record`` is a cheap
    no-op, ``snapshot``/``debug_traces`` keep their shapes) so a
    disabled replica still answers every scrape."""

    def __init__(self, replica_id, capacity=4096, enabled=True):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.replica_id = str(replica_id)
        self.capacity = int(capacity)
        self.enabled = bool(enabled)
        self._ring = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._recorded = 0
        self._dropped = 0
        # perf_counter -> wall anchor (one origin per process; callers
        # holding Request perf_counter stamps convert through wall()).
        # The anchor pairs a wall read with the perf_counter midpoint
        # of a bracket around it; the bracket width bounds how far a
        # scheduler stall between the two clock reads can skew every
        # later conversion. wall() keeps re-anchoring on the tightest
        # bracket seen, so one unlucky stall never sticks.
        self._anchor = self._read_anchor()

    @staticmethod
    def _read_anchor():
        p1 = time.perf_counter()
        w = time.time()
        p2 = time.perf_counter()
        return (p2 - p1, w, 0.5 * (p1 + p2))

    def wall(self, t_perf):
        """Convert a perf_counter timestamp from THIS process into
        epoch wall seconds through the recorder's anchor."""
        cand = self._read_anchor()
        if cand[0] < self._anchor[0]:
            self._anchor = cand
        _, wall0, perf0 = self._anchor
        return wall0 + (float(t_perf) - perf0)

    # ------------------------------------------------------ recording
    def record(self, ctx, name, t0, dur, attrs=None):
        """Append one span: ``t0``/``dur`` in wall seconds, parented
        on ``ctx.span_id``. Returns the new span id (None when
        disabled or ctx is None — callers never branch on it)."""
        if not self.enabled or ctx is None:
            return None
        span_id = os.urandom(8).hex()
        span = TraceSpan(ctx.trace_id, span_id, ctx.span_id,
                         str(name), self.replica_id, float(t0),
                         max(0.0, float(dur)),
                         dict(attrs) if attrs else None)
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(span)
            self._recorded += 1
        return span_id

    def record_root(self, ctx, name, t0, dur, attrs=None):
        """Append the trace's ROOT span: its span id IS ``ctx.span_id``
        (everything else recorded against ``ctx`` parents on it) and
        it has no parent. The router stamps one per finished request."""
        if not self.enabled or ctx is None:
            return None
        span = TraceSpan(ctx.trace_id, ctx.span_id, None, str(name),
                         self.replica_id, float(t0),
                         max(0.0, float(dur)),
                         dict(attrs) if attrs else None)
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(span)
            self._recorded += 1
        return ctx.span_id

    def span(self, ctx, name, attrs=None):
        """``with recorder.span(ctx, "kv/wire"):`` — wall-timed."""
        return _SpanTimer(self, ctx, name, attrs)

    # ------------------------------------------------------- querying
    def spans(self):
        with self._lock:
            return list(self._ring)

    def for_trace(self, trace_id):
        """Spans of one trace (as dicts), oldest first."""
        with self._lock:
            return [s.as_dict() for s in self._ring
                    if s.trace_id == trace_id]

    def trace_ids(self):
        """Distinct trace ids in the ring, most recent last."""
        seen = {}
        with self._lock:
            for s in self._ring:
                seen[s.trace_id] = True
        return list(seen)

    def snapshot(self):
        """The ``snapshot()["trace"]`` section (TRACE_SNAPSHOT_KEYS
        pinned; identical shape when disabled)."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "spans_recorded": self._recorded,
                "spans_dropped": self._dropped,
                "ring_occupancy": len(self._ring),
                "ring_capacity": self.capacity,
            }

    def debug_traces(self):
        """The ``/debug/traces`` JSON body. ``wall_time`` is this
        replica's clock at render time — the reading the assembler
        pairs with its own request/response stamps to bound skew."""
        with self._lock:
            spans = [s.as_dict() for s in self._ring]
        return {
            "replica_id": self.replica_id,
            "wall_time": round(time.time(), 6),
            "state": self.snapshot(),
            "spans": spans,
        }
