"""Fleet-side trace assembly: scrape, join, order, decompose.

The :class:`TraceAssembler` turns N per-replica span rings (the
``/debug/traces`` bodies, the router's ``/router/trace``, or live
:class:`~paddle_tpu.observability.trace.spans.TraceRecorder` objects)
into per-request end-to-end traces:

  * **clock-offset estimation** — every scraped body carries the
    replica's ``wall_time`` at render; the assembler's own
    request/response stamps around the scrape bound the true offset to
    ``[t_req - wall_time, t_resp - wall_time]`` (the classic NTP
    bound). The midpoint is the estimate, half the width the
    ambiguity. Span orderings that fall INSIDE the combined ambiguity
    of their sources are flagged ``skew_ambiguous`` — never silently
    reordered into a story the clocks can't support.
  * **assembly** — spans joined by trace_id across sources, shifted
    onto the assembler's clock, sorted; :class:`AssembledTrace` then
    answers the timeline, the nine-segment completeness check, and
    the wall accounting (window vs segment sum = the unattributed
    gap).
  * **rendering** — :func:`chrome_trace` (one pid per replica, one
    flow chain per trace linking the hops: the PR-4 flow machinery
    extended cross-process, valid under the same validator) and
    :func:`ttft_breakdown` (median/p99 ms per canonical segment over
    a cohort — the TTFT critical path as named numbers).

Pure stdlib on purpose: ``tools/trace_report.py`` loads this module by
file path and must never pay a jax import at CLI startup.
"""
import json
import time
import urllib.request

from .spans import CANONICAL_SEGMENTS

__all__ = ["TraceAssembler", "AssembledTrace", "chrome_trace",
           "ttft_breakdown"]


def _pct(values, q):
    """Linear-interpolation percentile over a small list; None when
    empty (stdlib-only — this module must import without numpy)."""
    if not values:
        return None
    vs = sorted(values)
    if len(vs) == 1:
        return vs[0]
    pos = (len(vs) - 1) * (q / 100.0)
    lo = int(pos)
    hi = min(lo + 1, len(vs) - 1)
    return vs[lo] + (vs[hi] - vs[lo]) * (pos - lo)


class _Source:
    __slots__ = ("replica_id", "spans", "offset", "ambiguity")

    def __init__(self, replica_id, spans, offset, ambiguity):
        self.replica_id = str(replica_id)
        self.spans = spans          # list of span dicts (replica clock)
        self.offset = float(offset)      # add to map onto our clock
        self.ambiguity = float(ambiguity)


class AssembledTrace:
    """One request's joined, clock-aligned, ordered span list."""

    def __init__(self, trace_id, spans):
        self.trace_id = trace_id
        # spans: dicts with adjusted "t0" + "skew_ambiguous" flags,
        # sorted by adjusted start time
        self.spans = spans

    @property
    def replicas(self):
        seen = {}
        for s in self.spans:
            seen[s["replica"]] = True
        return list(seen)

    def _canonical(self):
        return [s for s in self.spans if s["name"] in CANONICAL_SEGMENTS]

    def segments(self):
        """Wall milliseconds per canonical segment (summed across
        occurrences — a failover trace has two prefill attempts)."""
        out = {}
        for s in self._canonical():
            out[s["name"]] = out.get(s["name"], 0.0) \
                + s["dur"] * 1000.0
        return {k: round(v, 3) for k, v in out.items()}

    def missing_segments(self, required=CANONICAL_SEGMENTS):
        present = {s["name"] for s in self.spans}
        return [n for n in required if n not in present]

    @property
    def complete(self):
        return not self.missing_segments()

    def window_ms(self):
        """The TTFT accounting window: first canonical span start to
        last canonical span end (submit → decode/first_step end on a
        two-hop trace). None when no canonical span landed."""
        spans = self._canonical()
        if not spans:
            return None
        t0 = min(s["t0"] for s in spans)
        t1 = max(s["t0"] + s["dur"] for s in spans)
        return (t1 - t0) * 1000.0

    def unattributed_ms(self):
        """Window wall not covered by any canonical segment — the
        honesty metric: <10% of the window means the decomposition
        tells the whole TTFT story."""
        window = self.window_ms()
        if window is None:
            return None
        return max(0.0, window - sum(self.segments().values()))

    def unattributed_frac(self):
        window = self.window_ms()
        if not window:
            return None
        return self.unattributed_ms() / window

    def timeline(self):
        """Render-ready rows, ordered by (estimated) start time."""
        if not self.spans:
            return []
        t0 = min(s["t0"] for s in self.spans)
        rows = []
        for s in self.spans:
            rows.append({
                "t_rel_ms": round((s["t0"] - t0) * 1000.0, 3),
                "dur_ms": round(s["dur"] * 1000.0, 3),
                "replica": s["replica"],
                "name": s["name"],
                "skew_ambiguous": bool(s.get("skew_ambiguous")),
                "attrs": s.get("attrs") or {},
            })
        return rows

    def as_dict(self):
        return {
            "trace_id": self.trace_id,
            "replicas": self.replicas,
            "complete": self.complete,
            "missing_segments": self.missing_segments(),
            "window_ms": None if self.window_ms() is None
            else round(self.window_ms(), 3),
            "unattributed_ms": None if self.unattributed_ms() is None
            else round(self.unattributed_ms(), 3),
            "segments": self.segments(),
            "timeline": self.timeline(),
        }


class TraceAssembler:
    """Join per-replica span rings into per-request traces."""

    def __init__(self):
        self._sources = []

    # -------------------------------------------------------- sources
    def add_body(self, body, t_req=None, t_resp=None):
        """Ingest one ``/debug/traces`` body. ``t_req``/``t_resp`` are
        the assembler-clock stamps around the scrape that produced it;
        without them (a saved file, a same-process ring) the offset is
        taken as zero with zero ambiguity — correct when every source
        shares the host clock."""
        if not isinstance(body, dict) or "spans" not in body:
            raise ValueError("not a /debug/traces body (no spans)")
        offset, amb = 0.0, 0.0
        wall = body.get("wall_time")
        if t_req is not None and t_resp is not None \
                and isinstance(wall, (int, float)):
            lo = float(t_req) - float(wall)
            hi = float(t_resp) - float(wall)
            offset = (lo + hi) / 2.0
            amb = max(0.0, (hi - lo) / 2.0)
        spans = [s for s in body["spans"] if isinstance(s, dict)
                 and "trace_id" in s and "t0" in s and "dur" in s]
        self._sources.append(_Source(
            body.get("replica_id") or f"source{len(self._sources)}",
            spans, offset, amb))
        return self

    def add_recorder(self, recorder):
        """Ingest a live same-process TraceRecorder (zero offset)."""
        return self.add_body(recorder.debug_traces())

    def scrape(self, url, timeout=5.0, samples=3):
        """GET one replica's trace surface, stamping the round trip
        for the skew bound. A bare host:port scrapes
        ``/debug/traces``; give the full path for the router's
        ``/router/trace``.

        NTP-style sampling: take ``samples`` round trips and keep the
        tightest one. A scheduler stall inflates a round trip — and
        with it both the ambiguity and the midpoint offset error — so
        the fastest sample is the most truthful clock bound."""
        url = str(url).rstrip("/")
        if "://" not in url:
            url = "http://" + url
        if url.count("/") <= 2:   # no path component
            url += "/debug/traces"
        best = None
        for _ in range(max(1, int(samples))):
            t_req = time.time()
            with urllib.request.urlopen(url, timeout=timeout) as resp:
                raw = resp.read()
            t_resp = time.time()
            if best is None or (t_resp - t_req) < (best[1] - best[0]):
                best = (t_req, t_resp, raw)
        body = json.loads(best[2].decode("utf-8"))
        return self.add_body(body, t_req=best[0], t_resp=best[1])

    # ------------------------------------------------------- assembly
    def trace_ids(self):
        """Every trace id any source saw, in first-seen order."""
        seen = {}
        for src in self._sources:
            for s in src.spans:
                seen[s["trace_id"]] = True
        return list(seen)

    def assemble(self, trace_id):
        """One AssembledTrace (or None when no source saw the id):
        spans shifted onto the assembler clock, sorted by estimated
        start, skew-ambiguous orderings flagged."""
        spans = []
        for src in self._sources:
            for s in src.spans:
                if s["trace_id"] != trace_id:
                    continue
                d = dict(s)
                d["t0"] = float(s["t0"]) + src.offset
                d["dur"] = float(s["dur"])
                d["replica"] = s.get("replica") or src.replica_id
                d["_amb"] = src.ambiguity
                d["_src"] = id(src)
                spans.append(d)
        if not spans:
            return None
        spans.sort(key=lambda s: (s["t0"], s["name"]))
        # ordering honesty: when two adjacent spans come from
        # different sources and their start gap is inside the combined
        # clock ambiguity, the rendered order is an estimate — flag
        # both rather than silently presenting it as fact
        for a, b in zip(spans, spans[1:]):
            if a["_src"] == b["_src"]:
                continue
            if abs(b["t0"] - a["t0"]) < a["_amb"] + b["_amb"]:
                a["skew_ambiguous"] = True
                b["skew_ambiguous"] = True
        for s in spans:
            s.pop("_amb", None)
            s.pop("_src", None)
        return AssembledTrace(trace_id, spans)

    def assemble_all(self):
        return [t for t in (self.assemble(tid)
                            for tid in self.trace_ids())
                if t is not None]


# ---------------------------------------------------------- rendering
def chrome_trace(traces):
    """chrome://tracing export over assembled traces: one pid per
    replica, every span an "X" slice, one flow chain per trace whose
    s/t/f points ride the span starts — loadable next to (and valid
    under the same flow validator as) the PR-4 single-process export,
    now spanning processes."""
    traces = list(traces)
    events = []
    pids = {}
    for tr in traces:
        for s in tr.spans:
            if s["replica"] not in pids:
                pids[s["replica"]] = len(pids) + 1
    for replica, pid in sorted(pids.items(), key=lambda kv: kv[1]):
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": replica}})
    all_spans = [s for tr in traces for s in tr.spans]
    if not all_spans:
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    t_base = min(s["t0"] for s in all_spans)
    for tr in traces:
        fid = int(tr.trace_id[:12], 16)
        chain = sorted(tr.spans, key=lambda s: s["t0"])
        for i, s in enumerate(chain):
            pid = pids[s["replica"]]
            ts = round((s["t0"] - t_base) * 1e6, 3)
            dur = round(s["dur"] * 1e6, 3)
            args = dict(s.get("attrs") or {})
            args["trace_id"] = tr.trace_id
            if s.get("skew_ambiguous"):
                args["skew_ambiguous"] = True
            events.append({"ph": "X", "name": s["name"], "cat": "trace",
                           "ts": ts, "dur": dur, "pid": pid, "tid": 1,
                           "args": args})
            phase = "s" if i == 0 else \
                ("f" if i == len(chain) - 1 else "t")
            flow = {"ph": phase, "name": f"trace {tr.trace_id[:8]}",
                    "cat": "trace", "id": fid,
                    # strictly increasing inside the chain (ties in
                    # rounded span starts would shuffle s/t/f order);
                    # the offsets stay far under the validator's
                    # rounding slack, so every point still binds to
                    # its own span
                    "ts": round(ts + 0.001 * i, 3),
                    "pid": pid, "tid": 1,
                    "args": {"span": s["name"]}}
            if phase == "f":
                flow["bp"] = "e"    # enclosing-slice binding
            events.append(flow)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def ttft_breakdown(traces):
    """The TTFT critical-path decomposition over a cohort: median/p99
    milliseconds per canonical segment, the window, and the
    unattributed gap (PR 17's bimodal mystery as named numbers)."""
    traces = list(traces)
    per_seg = {name: [] for name in CANONICAL_SEGMENTS}
    windows, gaps, fracs = [], [], []
    complete = 0
    for tr in traces:
        segs = tr.segments()
        for name, ms in segs.items():
            per_seg[name].append(ms)
        w = tr.window_ms()
        if w is not None:
            windows.append(w)
            gaps.append(tr.unattributed_ms())
            fracs.append(tr.unattributed_frac())
        if tr.complete:
            complete += 1

    def stats(values):
        return {"median_ms": None if not values
                else round(_pct(values, 50), 3),
                "p99_ms": None if not values
                else round(_pct(values, 99), 3),
                "count": len(values)}

    out = {
        "count": len(traces),
        "complete": complete,
        "ttft": stats(windows),
        "segments": {name: stats(per_seg[name])
                     for name in CANONICAL_SEGMENTS},
        "unattributed": stats(gaps),
    }
    out["unattributed"]["median_frac"] = None if not fracs \
        else round(_pct(fracs, 50), 4)
    return out
