"""W3C-traceparent-style trace context: the propagated identity.

One :class:`TraceContext` names one distributed request: a 128-bit
``trace_id`` every process's spans join on, the 64-bit ``span_id`` of
the CALLER's span (the parent a receiving process hangs its spans
under), and a small JSON-safe ``baggage`` dict that rides every hop
(the router stamps its request tag there, so an engine-side span ring
can be grepped by router rid without a join).

The wire form is the W3C trace-context header value::

    00-<32 hex trace_id>-<16 hex span_id>-01

carried as a ``"traceparent"`` field on the gateway POST bodies and
inside the KV handoff payload (``"trace": {"traceparent", "baggage"}``
— see serving.kv_wire). Only version ``00`` with the sampled flag is
ever emitted; parsing accepts any flag byte.

``coerce`` is the graceful-degradation contract: whatever arrives —
None (a direct ``add_request`` with no router above it), a truncated
header, corrupted wire baggage, an old-format journal entry — the
caller gets a VALID context back and never an exception. A locally
minted root is marked ``minted_local`` so assembled traces can tell
"joined the fleet trace" from "started its own".
"""
import os
import re

__all__ = ["TraceContext", "TRACEPARENT_RE"]

TRACEPARENT_RE = re.compile(
    r"^[0-9a-f]{2}-([0-9a-f]{32})-([0-9a-f]{16})-[0-9a-f]{2}$")

# baggage hygiene: a handful of small scalar entries, never a payload
_MAX_BAGGAGE_ITEMS = 16
_MAX_BAGGAGE_CHARS = 256


def _new_trace_id():
    return os.urandom(16).hex()


def _new_span_id():
    return os.urandom(8).hex()


def _clean_baggage(baggage):
    """Sanitize a would-be baggage mapping: keep at most
    _MAX_BAGGAGE_ITEMS str-keyed scalar entries, drop the rest.
    Anything that isn't a mapping sanitizes to {} — corrupted baggage
    degrades to an empty bag, never an exception."""
    if not isinstance(baggage, dict):
        return {}
    out = {}
    for k, v in baggage.items():
        if len(out) >= _MAX_BAGGAGE_ITEMS:
            break
        if not isinstance(k, str):
            continue
        if isinstance(v, bool) or not isinstance(v, (str, int, float)):
            v = str(v)
        if isinstance(v, str) and len(v) > _MAX_BAGGAGE_CHARS:
            v = v[:_MAX_BAGGAGE_CHARS]
        out[k[:_MAX_BAGGAGE_CHARS]] = v
    return out


class TraceContext:
    """One request's propagated trace identity (immutable by
    convention: derive with :meth:`child`, never mutate in place)."""

    __slots__ = ("trace_id", "span_id", "baggage", "minted_local")

    def __init__(self, trace_id, span_id, baggage=None,
                 minted_local=False):
        self.trace_id = str(trace_id)
        self.span_id = str(span_id)
        self.baggage = _clean_baggage(baggage)
        self.minted_local = bool(minted_local)

    # ------------------------------------------------------- minting
    @classmethod
    def mint(cls, baggage=None, minted_local=False):
        """A fresh root context (the router's admission moment — or,
        via :meth:`coerce`, a local root for an orphan request)."""
        return cls(_new_trace_id(), _new_span_id(), baggage=baggage,
                   minted_local=minted_local)

    def child(self, baggage=None):
        """Derive a context for an outgoing hop: same trace, new span
        id (the callee's spans parent on it)."""
        bag = dict(self.baggage)
        bag.update(_clean_baggage(baggage))
        return TraceContext(self.trace_id, _new_span_id(), baggage=bag,
                            minted_local=self.minted_local)

    # ----------------------------------------------------- wire forms
    def to_traceparent(self):
        return f"00-{self.trace_id}-{self.span_id}-01"

    @classmethod
    def from_traceparent(cls, value, baggage=None):
        """Parse the header form; raises ValueError on malformed input
        (callers that must not raise go through :meth:`coerce`)."""
        m = TRACEPARENT_RE.match(str(value).strip().lower())
        if m is None:
            raise ValueError(
                f"malformed traceparent {str(value)[:64]!r}")
        return cls(m.group(1), m.group(2), baggage=baggage)

    def as_dict(self):
        """The JSON wire form carried on POST bodies and inside KV
        handoff payloads."""
        return {"traceparent": self.to_traceparent(),
                "baggage": dict(self.baggage)}

    # ---------------------------------------------------- degradation
    @classmethod
    def coerce(cls, obj):
        """ALWAYS returns a valid TraceContext; NEVER raises.

        Accepts a TraceContext (passed through), a traceparent string,
        a ``{"traceparent": ..., "baggage": ...}`` dict (the wire
        form), or garbage/None — the last two degrade to a locally
        minted root so an engine keeps serving whatever arrives."""
        if isinstance(obj, TraceContext):
            return obj
        try:
            if isinstance(obj, str):
                return cls.from_traceparent(obj)
            if isinstance(obj, dict):
                return cls.from_traceparent(
                    obj["traceparent"], baggage=obj.get("baggage"))
        except (KeyError, ValueError, TypeError, AttributeError):
            pass
        return cls.mint(minted_local=True)

    def __repr__(self):
        return (f"TraceContext({self.to_traceparent()!r}, "
                f"minted_local={self.minted_local})")
