"""Distributed request tracing: cross-replica propagation + assembly.

Since PR 14 (router) and PR 17 (prefill/decode disaggregation) a
request's life can span three processes — router queue → hop-1 prefill
replica → KV-wire transfer → hop-2 decode replica — while the flight
recorder (PR 4) only ever sees one engine. This package is the
Dapper-style answer, the same propagate-then-assemble design
DistServe-class disaggregated servers use to price their handoff:

  * :mod:`context` — a W3C-traceparent-style :class:`TraceContext`
    (trace_id, parent span id, baggage) minted by the router at
    admission and carried on every wire edge: the ``/v1/generate`` /
    ``/v1/prefill`` / ``/v1/import`` POST bodies, the KV handoff
    payload (so the decode-tier import joins the same trace), and the
    router journal (so a failover replay appears as sibling spans of
    the dead attempt under one trace_id). ``TraceContext.coerce``
    NEVER raises: a request arriving with a missing or malformed
    context gets a locally-minted root and keeps serving.
  * :mod:`spans` — per-process wall-anchored named spans
    (``router/queue``, ``router/dispatch``, ``prefill/queue``,
    ``prefill/compute``, ``kv/export``, ``kv/wire``, ``kv/import``,
    ``decode/queue``, ``decode/first_step`` + retry/hedge/failover)
    in a bounded ring, exposed per replica at ``/debug/traces`` (and
    ``/router/trace`` on the router).
  * :mod:`assembler` — the fleet-side :class:`TraceAssembler`:
    scrapes ``/debug/traces`` across replicas, joins spans by
    trace_id with per-replica clock-offset estimation (the scrape
    request/response timestamps bound the skew; ordering that falls
    inside the ambiguity window is FLAGGED, never silently
    reordered), and renders the end-to-end timeline, a
    chrome://tracing export (one pid per replica, flow events linking
    the hops — the PR-4 flow machinery extended cross-process) and
    the TTFT critical-path decomposition (median/p99 ms per segment
    over a cohort).

``tools/trace_report.py`` is the stdlib-only CLI over the assembler.
"""
from .context import TRACEPARENT_RE, TraceContext
from .spans import (CANONICAL_SEGMENTS, TRACE_SNAPSHOT_KEYS, TraceSpan,
                    TraceRecorder)
from .assembler import (AssembledTrace, TraceAssembler, chrome_trace,
                        ttft_breakdown)

__all__ = [
    "TraceContext", "TraceSpan", "TraceRecorder", "TraceAssembler",
    "AssembledTrace", "chrome_trace", "ttft_breakdown",
    "CANONICAL_SEGMENTS", "TRACE_SNAPSHOT_KEYS", "TRACEPARENT_RE",
]
