"""Build-config queries (reference: python/paddle/sysconfig.py —
get_include/get_lib for compiling extensions against the framework).

The TPU build's native pieces live in runtime_cpp/ and custom ops build
via utils.cpp_extension (C ABI, no framework headers required), so
these return the package-local include/lib locations.
"""
import os

__all__ = ["get_include", "get_lib"]


def get_include():
    """Directory containing C/C++ headers shipped with the package."""
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "include")


def get_lib():
    """Directory containing the native runtime shared objects."""
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return os.path.join(root, "runtime_cpp")
