"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = sum(p.size for p in layer._parameters.values()
                       if p is not None)
        n_train = sum(p.size for p in layer._parameters.values()
                      if p is not None and p.trainable)
        if not layer._sub_layers:  # leaf layers only in the table
            rows.append((name or type(layer).__name__,
                         type(layer).__name__, n_params))
        total_params += n_params
        trainable_params += n_train
    width = max([len(r[0]) for r in rows] + [10]) + 2
    lines = [f"{'Layer':<{width}}{'Type':<24}{'Params':>12}",
             "-" * (width + 36)]
    for name, typ, n in rows:
        lines.append(f"{name:<{width}}{typ:<24}{n:>12,}")
    lines.append("-" * (width + 36))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params,
            "trainable_params": trainable_params}
