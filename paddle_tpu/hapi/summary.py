"""paddle.summary (reference: python/paddle/hapi/model_summary.py)."""
import numpy as np


def summary(net, input_size=None, dtypes=None, input=None):  # noqa: A002
    rows = []
    total_params = 0
    trainable_params = 0
    for name, layer in net.named_sublayers(include_self=True):
        n_params = sum(p.size for p in layer._parameters.values()
                       if p is not None)
        n_train = sum(p.size for p in layer._parameters.values()
                      if p is not None and p.trainable)
        if not layer._sub_layers:  # leaf layers only in the table
            rows.append((name or type(layer).__name__,
                         type(layer).__name__, n_params))
        total_params += n_params
        trainable_params += n_train
    width = max([len(r[0]) for r in rows] + [10]) + 2
    lines = [f"{'Layer':<{width}}{'Type':<24}{'Params':>12}",
             "-" * (width + 36)]
    for name, typ, n in rows:
        lines.append(f"{name:<{width}}{typ:<24}{n:>12,}")
    lines.append("-" * (width + 36))
    lines.append(f"Total params: {total_params:,}")
    lines.append(f"Trainable params: {trainable_params:,}")
    print("\n".join(lines))
    return {"total_params": total_params,
            "trainable_params": trainable_params}


def flops(net, input_size, custom_ops=None, print_detail=False):
    """Analytic FLOPs count for a network (reference:
    python/paddle/hapi/dynamic_flops.py flops). Counted per leaf layer from
    layer hyper-parameters; custom_ops maps layer class -> fn(layer, in, out)
    returning flops."""
    import numpy as np
    from .. import nn

    if isinstance(input_size, (list, tuple)) and input_size and \
            isinstance(input_size[0], int):
        shapes = [tuple(input_size)]
    else:
        shapes = [tuple(s) for s in input_size]

    total = 0
    rows = []
    # run a forward with shape hooks to learn per-layer IO shapes
    import paddle_tpu as paddle
    xs = [paddle.zeros(list(s)) for s in shapes]
    records = []

    hooks = []

    def make_hook(layer):
        def hook(lyr, inputs, output):
            records.append((lyr, inputs, output))
        return hook

    for _, layer in net.named_sublayers(include_self=True):
        if not layer._sub_layers:
            hooks.append(layer.register_forward_post_hook(make_hook(layer)))
    was_training = net.training
    net.eval()
    try:
        with paddle.no_grad():
            net(*xs)
    finally:
        if was_training:
            net.train()
        for h in hooks:
            h.remove()

    for layer, inputs, output in records:
        f = 0
        out = output[0] if isinstance(output, (list, tuple)) else output
        o_numel = int(np.prod(out.shape)) if hasattr(out, "shape") else 0
        if custom_ops and type(layer) in custom_ops:
            f = custom_ops[type(layer)](layer, inputs, output)
        elif isinstance(layer, nn.Conv2D):
            kh, kw = layer._kernel_size
            cin = layer._in_channels
            f = o_numel * cin // layer._groups * kh * kw * 2
        elif isinstance(layer, nn.Linear):
            f = o_numel * layer.weight.shape[0] * 2
        elif isinstance(layer, (nn.BatchNorm2D, nn.BatchNorm1D, nn.BatchNorm,
                                nn.LayerNorm)):
            f = o_numel * 2
        elif isinstance(layer, (nn.ReLU, nn.Sigmoid, nn.Tanh, nn.GELU)):
            f = o_numel
        elif isinstance(layer, (nn.AvgPool2D, nn.MaxPool2D,
                                nn.AdaptiveAvgPool2D)):
            f = o_numel
        total += f
        if print_detail:
            rows.append((type(layer).__name__, f))
    if print_detail:
        for name, f in rows:
            print(f"{name:<28}{f:>16,}")
    print(f"Total Flops: {total}")
    return total
