"""Training callbacks (reference: python/paddle/hapi/callbacks.py:
Callback, ProgBarLogger, ModelCheckpoint, LRScheduler, EarlyStopping)."""
import numbers
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_begin(self, mode, logs=None):
        pass

    def on_end(self, mode, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_batch_begin(self, step, logs=None):
        pass

    def on_eval_batch_end(self, step, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks):
        self.callbacks = callbacks

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def on_begin(self, mode, logs=None):
        for c in self.callbacks:
            c.on_begin(mode, logs)

    def on_end(self, mode, logs=None):
        for c in self.callbacks:
            c.on_end(mode, logs)

    def on_epoch_begin(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_begin(epoch, logs)

    def on_epoch_end(self, epoch, logs=None):
        for c in self.callbacks:
            c.on_epoch_end(epoch, logs)

    def on_batch_begin(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_begin")(step, logs)

    def on_batch_end(self, mode, step, logs=None):
        for c in self.callbacks:
            getattr(c, f"on_{mode}_batch_end")(step, logs)


class ProgBarLogger(Callback):
    """ips (samples/sec) logging matches reference hapi/callbacks.py."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self.steps = 0
        self._t0 = time.perf_counter()
        self._samples = 0

    def on_train_batch_end(self, step, logs=None):
        logs = logs or {}
        self.steps += 1
        self._samples += logs.get("batch_size", 0)
        if self.verbose and step % self.log_freq == 0:
            dt = time.perf_counter() - self._t0
            ips = self._samples / dt if dt > 0 else 0.0
            items = []
            for k, v in logs.items():
                if k == "batch_size":
                    continue
                if isinstance(v, numbers.Number):
                    items.append(f"{k}: {v:.4f}")
                elif isinstance(v, (list, np.ndarray)):
                    items.append(f"{k}: {np.asarray(v).mean():.4f}")
            print(f"Epoch {self.epoch} step {step}: " + ", ".join(items) +
                  f" - {ips:.1f} samples/sec")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.perf_counter() - self._t0
            print(f"Epoch {epoch} done in {dt:.2f}s: {logs}")


class ModelCheckpoint(Callback):
    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and epoch % self.save_freq == 0:
            self.model.save(f"{self.save_dir}/{epoch}")

    def on_end(self, mode, logs=None):
        if mode == "train" and self.save_dir:
            self.model.save(f"{self.save_dir}/final")


class VisualDL(Callback):
    """Training-visualization writer (reference: hapi/callbacks.py
    VisualDL — scalars via visualdl.LogWriter). Here the scalars go to a
    TensorBoard events file (utils/tbwriter.py SummaryWriter) so any
    stock TensorBoard can render loss/metric curves; tags mirror the
    reference's `train/{loss,metric}` and `eval/...` naming."""

    def __init__(self, log_dir, log_freq=1):
        super().__init__()
        self.log_dir = log_dir
        self.log_freq = int(log_freq)
        self.writer = None
        self._global_step = 0

    def _w(self):
        if self.writer is None:
            from ..utils.tbwriter import SummaryWriter
            self.writer = SummaryWriter(self.log_dir)
        return self.writer

    def _write_logs(self, prefix, logs, step):
        for k, v in (logs or {}).items():
            if k == "batch_size":
                continue
            if isinstance(v, numbers.Number):
                self._w().add_scalar(f"{prefix}/{k}", v, step)
            elif isinstance(v, (list, tuple, np.ndarray)):
                arr = np.asarray(v, dtype=np.float64).reshape(-1)
                if arr.size:
                    self._w().add_scalar(f"{prefix}/{k}",
                                         float(arr.mean()), step)

    def on_train_batch_end(self, step, logs=None):
        self._global_step += 1
        if self._global_step % self.log_freq == 0:
            self._write_logs("train", logs, self._global_step)

    def on_epoch_end(self, epoch, logs=None):
        self._write_logs("train_epoch", logs, epoch)
        self._w().flush()

    def on_eval_end(self, logs=None):
        self._write_logs("eval", logs, self._global_step)
        self._w().flush()

    def on_end(self, mode, logs=None):
        if mode == "eval":
            self.on_eval_end(logs)
        if self.writer is not None:
            self.writer.flush()
            if mode == "train":
                self.writer.close()
                self.writer = None  # a later fit() reopens cleanly


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        return getattr(opt, "_lr_scheduler", None) if opt else None

    def on_train_batch_end(self, step, logs=None):
        s = self._sched()
        if self.by_step and s is not None:
            s.step()

    def on_epoch_end(self, epoch, logs=None):
        s = self._sched()
        if self.by_epoch and s is not None:
            s.step()


class EarlyStopping(Callback):
    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.best = None
        self.wait = 0
        if mode == "auto":
            mode = "min" if "loss" in monitor else "max"
        self.mode = mode

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        cur = logs.get(self.monitor)
        if cur is None:
            return
        if isinstance(cur, (list, np.ndarray)):
            cur = float(np.asarray(cur).mean())
        improved = (self.best is None or
                    (self.mode == "min" and cur < self.best - self.min_delta) or
                    (self.mode == "max" and cur > self.best + self.min_delta))
        if improved:
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


def config_callbacks(callbacks=None, model=None, epochs=None, steps=None,
                     log_freq=2, verbose=2, save_freq=1, save_dir=None,
                     metrics=None, mode="train"):
    cbks = list(callbacks) if callbacks else []
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks = [ProgBarLogger(log_freq, verbose=verbose)] + cbks
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks = cbks + [LRScheduler()]
    if save_dir and not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks = cbks + [ModelCheckpoint(save_freq, save_dir)]
    cl = CallbackList(cbks)
    cl.set_model(model)
    cl.set_params({"epochs": epochs, "steps": steps, "verbose": verbose,
                   "metrics": metrics or []})
    return cl
