"""High-level Model API.

Reference parity: python/paddle/hapi/model.py:878 Model (fit:1523,
evaluate:1753, predict:1855, train_batch/eval_batch) with BOTH adapters:
the dygraph path runs ops eagerly; with paddle.enable_static() active,
train/eval/predict batches run through a to_static-COMPILED whole step —
the TPU-native equivalent of the reference's StaticGraphAdapter
(model.py:249: builds a static Program per mode and runs it in the
executor; here the captured trace IS that program, compiled by XLA).
Both adapters share the callback/metric/loop plumbing, and metrics stay
eager over the step's returned outputs exactly like the reference
adapter feeds fetched outputs to Metric.update.
"""
import numpy as np

from .. import profiler as _profiler
from ..core.tensor import Tensor
from ..core.dispatch import no_grad
from ..io import DataLoader
from ..ops import math as math_ops
from . import callbacks as cb_mod


def _in_static_mode():
    from ..static import _static_mode
    return bool(_static_mode[0])


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False
        # static-adapter compiled steps, built lazily per mode (the
        # train/eval distinction must be baked into separate programs:
        # dropout/BN behave differently)
        self._static_steps = {}

    # ---- static adapter (reference: hapi/model.py:249
    # StaticGraphAdapter) --------------------------------------------------
    def _static_step(self, mode):
        step = self._static_steps.get(mode)
        if step is not None:
            return step
        from ..jit import to_static
        model = self

        if mode == "train":
            def raw(ins, labs, update):
                outputs = model.network(*ins)
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                losses = model._loss(*(outs + [l for l in labs
                                               if l is not None]))
                loss_list = losses if isinstance(losses, (list, tuple)) \
                    else [losses]
                total = loss_list[0]
                for l in loss_list[1:]:
                    total = math_ops.add(total, l)
                total.backward()
                if update:
                    model._optimizer.step()
                    model._optimizer.clear_grad()
                return list(loss_list), list(outs)
        elif mode == "train_window":
            # gradient accumulation, static style: the WINDOW is the
            # compiled unit — k micro-batch backwards accumulate grads
            # in-trace, then one optimizer step. Splitting update/no-
            # update into separate compiled programs would break the
            # grad dataflow between them (compiled programs capture
            # tensors by identity at record time), and one program per
            # window is the better XLA program anyway (the fleet
            # GradientMerge meta-optimizer compiles the same shape).
            def raw(ins_seq, labs_seq):
                per = []
                for ins, labs in zip(ins_seq, labs_seq):
                    outputs = model.network(*ins)
                    outs = list(outputs) if isinstance(
                        outputs, (list, tuple)) else [outputs]
                    losses = model._loss(*(outs + [l for l in labs
                                                   if l is not None]))
                    loss_list = list(losses) if isinstance(
                        losses, (list, tuple)) else [losses]
                    total = loss_list[0]
                    for l in loss_list[1:]:
                        total = math_ops.add(total, l)
                    total.backward()
                    per.append((loss_list, outs))
                model._optimizer.step()
                model._optimizer.clear_grad()
                return per
        elif mode == "eval":
            def raw(ins, labs):
                outputs = model.network(*ins)
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                if model._loss is None:
                    return [], list(outs)
                losses = model._loss(*(outs + [l for l in labs
                                               if l is not None]))
                loss_list = losses if isinstance(losses, (list, tuple)) \
                    else [losses]
                return list(loss_list), list(outs)
        else:
            def raw(ins):
                outputs = model.network(*ins)
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                return list(outs)

        step = to_static(raw)
        self._static_steps[mode] = step
        return step

    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        # compiled static steps close over loss/optimizer at trace
        # time: a re-prepare must invalidate them or the old pair
        # stays baked into the XLA program
        self._static_steps = {}
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]

    # ---- single-batch ops ------------------------------------------------
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in ins]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        labs = [y if isinstance(y, Tensor) or y is None
                else Tensor(np.asarray(y)) for y in labs]
        # the train-step scope feeds the XLA trace, the chrome host
        # timeline and the registry span counters in one shot (see
        # paddle_tpu.observability) — same discipline as the serving
        # engine's serving/* scopes
        with _profiler.record_scope("hapi/train_batch"):
            if _in_static_mode():
                loss_list, outs = self._static_step("train")(
                    ins, labs, bool(update))
            else:
                outputs = self.network(*ins)
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                losses = self._loss(*(outs
                                      + [l for l in labs
                                         if l is not None]))
                loss_list = losses if isinstance(losses, (list, tuple)) \
                    else [losses]
                total = loss_list[0]
                for l in loss_list[1:]:
                    total = math_ops.add(total, l)
                total.backward()
                if update:
                    self._optimizer.step()
                    self._optimizer.clear_grad()
        metrics = []
        for m in self._metrics:
            metrics.append(m.update(m.compute(*(outs + [l for l in labs
                                                        if l is not None]))))
        vals = [float(l.numpy()) for l in loss_list]
        return (vals, metrics) if metrics else vals

    @no_grad()
    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in ins]
        labs = labels if isinstance(labels, (list, tuple)) else [labels]
        labs = [y if isinstance(y, Tensor) or y is None
                else Tensor(np.asarray(y)) for y in labs]
        with _profiler.record_scope("hapi/eval_batch"):
            if _in_static_mode():
                loss_list, outs = self._static_step("eval")(ins, labs)
            else:
                outputs = self.network(*ins)
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
                loss_list = None
                if self._loss is not None:
                    losses = self._loss(*(outs + [l for l in labs
                                                  if l is not None]))
                    loss_list = losses \
                        if isinstance(losses, (list, tuple)) else [losses]
        metrics = []
        for m in self._metrics:
            metrics.append(m.update(m.compute(*(outs + [l for l in labs
                                                        if l is not None]))))
        if self._loss is not None and loss_list is not None:
            vals = [float(l.numpy()) for l in loss_list]
            return (vals, metrics) if metrics else vals
        return ([], metrics)

    @no_grad()
    def predict_batch(self, inputs):
        self.network.eval()
        ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        ins = [x if isinstance(x, Tensor) else Tensor(np.asarray(x))
               for x in ins]
        with _profiler.record_scope("hapi/predict_batch"):
            if _in_static_mode():
                outs = self._static_step("predict")(ins)
            else:
                outputs = self.network(*ins)
                outs = outputs if isinstance(outputs, (list, tuple)) \
                    else [outputs]
        return [o.numpy() for o in outs]

    # ---- loops -----------------------------------------------------------
    def _to_loader(self, data, batch_size, shuffle):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle)

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1,
            verbose=2, drop_last=False, shuffle=True, num_workers=0,
            callbacks=None, accumulate_grad_batches=1, num_iters=None):
        train_loader = self._to_loader(train_data, batch_size, shuffle)
        eval_loader = self._to_loader(eval_data, batch_size, False)
        cbks = cb_mod.config_callbacks(callbacks, model=self,
                                       epochs=epochs,
                                       steps=_safe_len(train_loader),
                                       log_freq=log_freq,
                                       save_freq=save_freq,
                                       save_dir=save_dir,
                                       verbose=verbose,
                                       metrics=self._metrics_names())
        cbks.on_begin("train")
        self.stop_training = False
        it_count = 0
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            train_logs = {}
            n_steps = _safe_len(train_loader)
            k = max(1, int(accumulate_grad_batches))
            # static mode compiles the whole accumulation window as ONE
            # program (see _static_step "train_window")
            use_window = _in_static_mode() and k > 1
            window = []
            pending = False
            for step, batch in enumerate(train_loader):
                ins, labs = _split_batch(batch)
                if use_window:
                    window.append((step, ins, labs))
                    if len(window) == k or (n_steps is not None
                                            and step + 1 == n_steps):
                        train_logs = self._run_static_window(
                            window, cbks, batch_size)
                        window = []
                else:
                    cbks.on_batch_begin("train", step, {})
                    # gradient accumulation (reference model.py:2059):
                    # the optimizer steps every k batches (and on the
                    # final batch); grads sum across the in-between
                    # backwards since clear_grad only runs on update
                    update = ((step + 1) % k == 0
                              or (n_steps is not None
                                  and step + 1 == n_steps))
                    res = self.train_batch(ins, labs, update=update)
                    pending = not update
                    train_logs = self._pack_logs(res, batch_size)
                    cbks.on_batch_end("train", step, train_logs)
                it_count += 1
                if (num_iters is not None and it_count >= num_iters) or \
                        self.stop_training:
                    break
            if window:
                # tail window (unknown-length loader / early break)
                train_logs = self._run_static_window(window, cbks,
                                                     batch_size)
            if pending:
                # unknown-length loader tail: the last batches ran with
                # update=False — apply their accumulated grads instead
                # of dropping them (or leaking them into the next
                # epoch's first step)
                self._optimizer.step()
                self._optimizer.clear_grad()
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_res = self.evaluate(eval_loader, verbose=0)
                for k, v in eval_res.items():
                    train_logs["eval_" + k] = v
            cbks.on_epoch_end(epoch, train_logs)
            if self.stop_training or (num_iters is not None
                                      and it_count >= num_iters):
                break
        cbks.on_end("train", {})

    def _run_static_window(self, window, cbks, batch_size):
        """Execute one static-mode accumulation window (compiled as a
        single program) and fire the per-batch callbacks/metrics/logs
        in order."""
        self.network.train()

        def coerce(xs):
            return [x if isinstance(x, Tensor) or x is None
                    else Tensor(np.asarray(x)) for x in xs]

        ins_seq = [coerce(ins) for _, ins, _ in window]
        labs_seq = [coerce(labs) for _, _, labs in window]
        with _profiler.record_scope("hapi/train_window"):
            results = self._static_step("train_window")(ins_seq,
                                                        labs_seq)
        logs = {}
        for (step, _, _), labs, (loss_list, outs) in zip(window, labs_seq,
                                                         results):
            cbks.on_batch_begin("train", step, {})
            metrics = []
            for m in self._metrics:
                metrics.append(m.update(m.compute(
                    *(outs + [l for l in labs if l is not None]))))
            vals = [float(l.numpy()) for l in loss_list]
            res = (vals, metrics) if metrics else vals
            logs = self._pack_logs(res, batch_size)
            cbks.on_batch_end("train", step, logs)
        return logs

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None):
        loader = self._to_loader(eval_data, batch_size, False)
        for m in self._metrics:
            m.reset()
        losses = []
        for batch in loader:
            ins, labs = _split_batch(batch)
            res = self.eval_batch(ins, labs)
            if isinstance(res, tuple):
                losses.extend(res[0])
            else:
                losses.extend(res)
        out = {}
        if losses:
            out["loss"] = float(np.mean(losses))
        for m in self._metrics:
            name = m.name()
            acc = m.accumulate()
            if isinstance(name, list):
                for n, a in zip(name, acc):
                    out[n] = a
            else:
                out[name] = acc
        return out

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, callbacks=None, verbose=1):
        loader = self._to_loader(test_data, batch_size, False)
        outputs = []
        # datasets that yield (inputs, label) pairs: drop the label column
        # when a loss was configured (reference Model tracks _inputs/_labels
        # specs; we infer from prepare())
        for batch in loader:
            ins, _ = _split_batch(batch, has_label=self._loss is not None)
            outputs.append(self.predict_batch(ins))
        if stack_outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs], axis=0)
                    for i in range(n_out)]
        return outputs

    # ---- persistence ------------------------------------------------------
    def save(self, path, training=True):
        from ..framework.io_utils import save as psave
        psave(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            psave(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework.io_utils import load as pload
        state = pload(path + ".pdparams")
        self.network.set_state_dict(state)
        import os
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(pload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .summary import summary as _summary
        return _summary(self.network, input_size, dtypes=dtype)

    # ---- helpers ----------------------------------------------------------
    def _metrics_names(self):
        names = ["loss"]
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _pack_logs(self, res, batch_size):
        logs = {"batch_size": batch_size}
        if isinstance(res, tuple):
            losses, metrics = res
        else:
            losses, metrics = res, []
        if losses:
            logs["loss"] = losses[0] if len(losses) == 1 else losses
        for m, val in zip(self._metrics, metrics):
            n = m.name()
            if isinstance(n, list):
                for nn_, v in zip(n, val):
                    logs[nn_] = v
            else:
                logs[n] = val
        return logs


def _split_batch(batch, has_label=True):
    if isinstance(batch, (list, tuple)):
        if len(batch) >= 2 and has_label:
            return list(batch[:-1]), [batch[-1]]
        return list(batch), [None]
    return [batch], [None]


def _safe_len(loader):
    try:
        return len(loader)
    except TypeError:
        return None
