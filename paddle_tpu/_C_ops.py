"""Reference: python/paddle/_C_ops.py — re-exports every generated
per-op fast entry point (pybind/op_function_generator.cc's
`imperative_<op>` functions, the dygraph hot path).

Here the equivalent of a generated C entry point is the registered Op
object itself: calling it dispatches straight into the cached-
executable engine (and the lazy micro-trace when active) with no
Python op-assembly layer in between — the same role `_C_ops.matmul`
plays in the reference call stack (SURVEY §3.1). Ops resolve lazily by
name (and the wrapper is cached in the module dict, so repeat accesses
are plain attribute lookups).
"""
__all__ = []

# the generated entry points' attr spellings differ from the op
# kernels' keyword names for a few hot ops
_ATTR_ALIASES = {"trans_x": "transpose_x", "trans_y": "transpose_y"}

# the reference's generated functions fall back to op-registered attr
# defaults when a call omits attrs; the registry kernels use required
# keyword-only attrs, so the common defaults live here
_DEFAULTS = {
    "matmul_v2": {"transpose_x": False, "transpose_y": False},
    "matmul": {"transpose_x": False, "transpose_y": False},
    "softmax": {"axis": -1},
    "concat": {"axis": 0},
}


def _wrap(op):
    """Adapt the reference _C_ops calling convention — positional
    tensors followed by alternating ('attr_name', value) pairs, e.g.
    _C_ops.matmul_v2(x, y, 'trans_x', False, 'trans_y', False) — onto
    the registry Op's (tensors..., **attrs) signature."""
    import inspect

    try:
        required = {
            p.name for p in inspect.signature(op.fn).parameters.values()
            if p.kind is inspect.Parameter.KEYWORD_ONLY
            and p.default is inspect.Parameter.empty}
    except (TypeError, ValueError):
        required = set()
    defaults = _DEFAULTS.get(op.name, {})

    def call(*args, **kwargs):
        pos = []
        i = 0
        while i < len(args) and not isinstance(args[i], str):
            pos.append(args[i])
            i += 1
        attrs = dict(kwargs)
        while i + 1 < len(args):
            k = args[i]
            attrs[_ATTR_ALIASES.get(k, k)] = args[i + 1]
            i += 2
        missing = required - attrs.keys()
        for k in missing & defaults.keys():
            attrs[k] = defaults[k]
        still = required - attrs.keys()
        if still:
            raise TypeError(
                f"_C_ops.{op.name} requires attrs {sorted(still)} "
                f"(pass as keywords or alternating name/value pairs)")
        return op(*pos, **attrs)

    call.__name__ = op.name
    call.op = op
    return call


def __getattr__(name):
    import importlib

    from .core.dispatch import _REGISTRY

    if name not in _REGISTRY:
        # op modules register on import; load them before declaring
        # the name missing (real import errors propagate — masking
        # them as 'no registered op' would misdirect debugging)
        for mod in ("ops", "ops.linalg", "ops.sequence", "nn.functional",
                    "vision.ops"):
            importlib.import_module(f"paddle_tpu.{mod}")
    if name in _REGISTRY:
        fn = _wrap(_REGISTRY[name])
        globals()[name] = fn  # cache: later accesses skip __getattr__
        return fn
    raise AttributeError(
        f"no registered op {name!r} (see paddle_tpu.core.dispatch)")


def __dir__():
    from .core.dispatch import _REGISTRY
    return sorted(_REGISTRY)
