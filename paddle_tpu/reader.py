"""paddle.reader decorators (reference: python/paddle/reader/decorator.py:
map_readers, shuffle, chain, compose, buffered, firstn, cache,
xmap_readers). A "reader" is a zero-arg callable returning an iterable of
samples — the pre-2.0 data API still used by fleet dataset pipelines.
"""
import itertools
import queue
import random as _random
import threading


def map_readers(func, *readers):
    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)
    return reader


def shuffle(reader, buf_size):
    def shuffled():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) >= buf_size:
                _random.shuffle(buf)
                yield from buf
                buf = []
        _random.shuffle(buf)
        yield from buf
    return shuffled


def chain(*readers):
    def chained():
        return itertools.chain(*[r() for r in readers])
    return chained


class ComposeNotAligned(ValueError):
    """Reference: reader/decorator.py ComposeNotAligned — raised when
    composed readers yield different numbers of samples."""


def compose(*readers, **kwargs):
    check_alignment = kwargs.get("check_alignment", True)
    _end = object()

    def composed():
        rs = [iter(r()) for r in readers]
        while True:
            vals = [next(it, _end) for it in rs]
            if all(v is _end for v in vals):
                return
            if any(v is _end for v in vals):
                if check_alignment:
                    raise ComposeNotAligned(
                        "readers yield different sample counts")
                return  # unchecked: stop at the shortest reader
            out = ()
            for v in vals:
                out += v if isinstance(v, tuple) else (v,)
            yield out
    return composed


def buffered(reader, size):
    """Background-thread prefetch of up to `size` samples (reference
    decorator.py buffered — the python-side analogue of the C++
    buffered_reader double-buffering)."""
    end = object()

    def buffered_reader():
        q = queue.Queue(maxsize=size)

        def fill():
            try:
                for s in reader():
                    q.put(s)
                q.put(end)
            except BaseException as e:  # propagate to the consumer
                q.put(e)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            s = q.get()
            if s is end:
                break
            if isinstance(s, BaseException):
                raise s
            yield s
    return buffered_reader


def firstn(reader, n):
    def firstn_reader():
        return itertools.islice(reader(), n)
    return firstn_reader


def cache(reader):
    all_data = []
    filled = []

    def cached():
        if not filled:
            all_data.extend(reader())
            filled.append(True)
        return iter(all_data)
    return cached


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Thread-pool map over a reader, streaming with at most buffer_size
    samples in flight (reference decorator.py xmap_readers); order=True
    preserves input order."""
    if order:
        def ordered():
            return map(mapper, reader())
        return ordered

    _end = object()

    def xreader():
        in_q = queue.Queue(maxsize=max(1, buffer_size))
        out_q = queue.Queue(maxsize=max(1, buffer_size))

        def feed():
            try:
                for s in reader():
                    in_q.put(s)
            except BaseException as e:
                in_q.put(e)
            for _ in range(process_num):
                in_q.put(_end)

        def work():
            while True:
                s = in_q.get()
                if s is _end:
                    out_q.put(_end)
                    return
                if isinstance(s, BaseException):
                    out_q.put(s)
                    return
                try:
                    out_q.put(mapper(s))
                except BaseException as e:
                    out_q.put(e)  # deliver, never deadlock the consumer

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True)
                   for _ in range(process_num)]
        for t in workers:
            t.start()
        finished = 0
        while finished < process_num:
            r = out_q.get()
            if r is _end:
                finished += 1
                continue
            if isinstance(r, BaseException):
                raise r
            yield r
    return xreader
