"""paddle_tpu: a TPU-native deep-learning framework with the capability set
of PaddlePaddle (reference: sneaxiy/Paddle ~v2.1), re-designed for JAX/XLA.

Top-level namespace mirrors `paddle.*` (reference: python/paddle/__init__.py):
tensor creation/math ops, nn, optimizer, amp, io, jit, distributed, vision,
plus device/dtype/flags management. The execution core is XLA via jax —
eager ops are per-op jit-compiled executables, `paddle_tpu.jit.to_static`
captures whole training steps as single XLA programs, and distribution is
expressed over `jax.sharding.Mesh` axes rather than NCCL rings.
"""

from .core import dtype as _dtype_mod
from .core.dtype import (  # noqa: F401
    bool_ as bool, int8, uint8, int16, int32, int64, float16, bfloat16,  # noqa: A004
    float32, float64, complex64, complex128,
    set_default_dtype, get_default_dtype,
)
from .core.device import (  # noqa: F401
    set_device, get_device, is_compiled_with_cuda, is_compiled_with_tpu,
    device_count, CPUPlace, TPUPlace, Place,
)
from .core.flags import set_flags, get_flags  # noqa: F401
from .core.tensor import Tensor, Parameter  # noqa: F401
from .core import errors  # noqa: F401
from .core.dispatch import no_grad, enable_grad, is_grad_enabled  # noqa: F401
from .core.rng import seed, default_generator  # noqa: F401
from .core import trace as _trace  # noqa: F401

from . import ops  # patches Tensor methods  # noqa: F401
from .ops.creation import (  # noqa: F401
    to_tensor, zeros, ones, full, empty, zeros_like, ones_like, full_like,
    empty_like, arange, linspace, logspace, eye, tril, triu, diag, diagflat,
    assign, clone, uniform, rand, randn, normal, randint, randperm,
    bernoulli, multinomial,
)
from .ops.math import (  # noqa: F401
    add, subtract, multiply, divide, floor_divide, remainder, mod, pow,  # noqa: A004
    maximum, minimum, fmax, fmin, matmul, mm, bmm, dot, mv, addmm, abs,  # noqa: A004
    neg, exp, expm1, log, log2, log10, log1p, sqrt, rsqrt, square, sin, cos,
    tan, asin, acos, atan, sinh, cosh, tanh, asinh, acosh, atanh, floor,
    ceil, round, trunc, frac, sign, reciprocal, erf, erfinv, lgamma,  # noqa: A004
    digamma, sigmoid, cast, scale, clip, lerp, cumsum, cumprod, isnan,
    isinf, isfinite, einsum, atan2, hypot, logit, nan_to_num, increment,
    stanh, kron, inner, outer, trace, diff, deg2rad, rad2deg, angle, conj,
    real, imag, heaviside, logaddexp, multiply as elementwise_mul,
    renorm, vander, logcumsumexp, trapezoid, cumulative_trapezoid,
    polygamma, igamma, i0,
)
from .ops.reduction import (  # noqa: F401
    sum, mean, max, min, prod, all, any, std, var, median, logsumexp, norm,  # noqa: A004
    dist, amax, amin, count_nonzero, nansum, nanmean, quantile,
    nanmedian, nanquantile,
)
from .ops.manipulation import (  # noqa: F401
    reshape, transpose, t, flatten, squeeze, unsqueeze, concat, stack,
    split, chunk, unbind, slice, gather, gather_nd, scatter, scatter_nd_add,  # noqa: A004
    index_select, index_sample, masked_select, masked_fill, tile, expand,
    expand_as, broadcast_to, broadcast_tensors, flip, roll, rot90,
    repeat_interleave, where, meshgrid, numel, shape, take_along_axis,
    put_along_axis, unstack, shard_index, unfold, strided_slice,
    moveaxis, index_add, index_add_, index_fill, index_fill_, tensordot,
    as_real, as_complex, view_as_real, view_as_complex,
)
from .ops.logic import (  # noqa: F401
    equal, not_equal, greater_than, greater_equal, less_than, less_equal,
    logical_and, logical_or, logical_not, logical_xor, bitwise_and,
    bitwise_or, bitwise_xor, bitwise_not, isclose, allclose, equal_all,
    is_empty, is_tensor,
)
from .ops.search import (  # noqa: F401
    argmax, argmin, argsort, sort, topk, nonzero, unique, kthvalue, mode,
    searchsorted, bincount, bucketize,
)
from .ops.nn_ops import one_hot  # noqa: F401
from .ops import linalg  # noqa: F401
from .ops.linalg import (  # noqa: F401
    cholesky, det, slogdet, matrix_power, pinv, lstsq, solve,
    triangular_solve, cholesky_solve, matrix_rank, multi_dot, svd, qr,
    eig, eigh, eigvalsh, lu, householder_product, corrcoef, cov,
)

from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import autograd  # noqa: F401
from . import metric  # noqa: F401
from . import framework  # noqa: F401
from .framework.io_utils import save, load  # noqa: F401
from . import static  # noqa: F401
from .autograd import grad  # noqa: F401
from . import device  # noqa: F401
from . import distributed  # noqa: F401
from . import vision  # noqa: F401
from . import text  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401
from . import tensor  # noqa: F401
from . import utils  # noqa: F401
from . import distribution  # noqa: F401
from . import incubate  # noqa: F401
from . import quantization  # noqa: F401
from . import regularizer  # noqa: F401
from . import hub  # noqa: F401
from . import onnx  # noqa: F401
from . import reader  # noqa: F401
from . import compat  # noqa: F401
from . import sysconfig  # noqa: F401
from . import dataset  # noqa: F401
from . import fluid  # noqa: F401
from .hapi import callbacks  # noqa: F401
from .ops import linalg  # noqa: F401
from .hapi.model import Model  # noqa: F401
from .hapi import summary  # noqa: F401

import numpy as _np

DataParallel = None  # set by distributed.parallel import below


def _late_bind():
    global DataParallel
    from .distributed.parallel import DataParallel as _DP
    DataParallel = _DP


_late_bind()

from . import version  # noqa: F401
from . import _C_ops  # noqa: F401

__version__ = version.full_version


def disable_static(place=None):
    from . import static as _static
    _static._disable()


def enable_static():
    from . import static as _static
    _static._enable()


def in_dynamic_mode():
    from . import static as _static
    return not _static._static_mode[0]


def get_cudnn_version():
    return None


def is_grad_enabled_():
    return is_grad_enabled()


def rank(x):
    return to_tensor(_np.asarray(x.ndim if isinstance(x, Tensor) else _np.ndim(x)))


# --- remaining reference top-level surface (python/paddle/__init__.py) ---
from .ops.math import add_n, cross, histogram, floor_mod, tanh_  # noqa: F401,E402
from .ops.manipulation import (  # noqa: F401,E402
    diagonal, multiplex, reverse, crop, crop_tensor, scatter_nd, scatter_,
    squeeze_, reshape_, unsqueeze_, tolist, broadcast_shape,
)
from .ops.creation import standard_normal, create_parameter  # noqa: F401,E402
from .ops.linalg import cholesky, inverse  # noqa: F401,E402
from .nn.initializer import ParamAttr  # noqa: F401,E402
from .core.device import (  # noqa: F401,E402
    CUDAPlace, CUDAPinnedPlace, XPUPlace, NPUPlace,
    is_compiled_with_xpu, is_compiled_with_npu, is_compiled_with_rocm,
)

VarBase = Tensor  # reference alias: paddle/fluid/imperative VarBase
dtype = _dtype_mod.DType  # paddle.dtype class alias


def enable_dygraph(place=None):
    return disable_static(place)


def disable_dygraph():
    return enable_static()


def in_dygraph_mode():
    return in_dynamic_mode()


def set_grad_enabled(mode):
    """Context manager toggling autograd (reference:
    python/paddle/framework/random.py area / torch-parity API)."""
    return enable_grad() if mode else no_grad()


_print_options = {"precision": 8, "threshold": 1000, "edgeitems": 3,
                  "linewidth": 80, "sci_mode": None}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """Reference: python/paddle/tensor/to_string.py set_printoptions."""
    kw = {}
    if precision is not None:
        _print_options["precision"] = precision
        kw["precision"] = precision
    if threshold is not None:
        _print_options["threshold"] = threshold
        kw["threshold"] = threshold
    if edgeitems is not None:
        _print_options["edgeitems"] = edgeitems
        kw["edgeitems"] = edgeitems
    if linewidth is not None:
        _print_options["linewidth"] = linewidth
        kw["linewidth"] = linewidth
    if sci_mode is not None:
        _print_options["sci_mode"] = sci_mode
        kw["suppress"] = not sci_mode
    _np.set_printoptions(**kw)


def get_cuda_rng_state():
    """CUDA shim: TPU RNG is stateless PRNG keys; returns the current seed
    state for checkpoint parity."""
    from .core import rng as _rng
    return [_rng.get_state()]


def set_cuda_rng_state(state):
    from .core import rng as _rng
    if state:
        _rng.set_state(state[0])


def monkey_patch_math_varbase():
    """No-op: Tensor operators are patched at import (ops/__init__.py)."""
    return None


def monkey_patch_variable():
    return None


def check_shape(shape):
    """Static-graph shape validation helper (reference:
    python/paddle/fluid/layers/utils.py check_shape)."""
    for s in shape if not isinstance(shape, (int,)) else [shape]:
        if isinstance(s, int) and s < -1:
            raise ValueError(f"invalid dim {s} in shape {shape}")


def batch(reader, batch_size, drop_last=False):
    """Batch a sample reader into a batch reader (reference:
    python/paddle/fluid/io.py batch / python/paddle/batch)."""
    def batch_reader():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batch_reader


def flops(net, input_size, custom_ops=None, print_detail=False):
    from .hapi.summary import flops as _flops
    return _flops(net, input_size, custom_ops=custom_ops,
                  print_detail=print_detail)
