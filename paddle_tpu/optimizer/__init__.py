"""paddle.optimizer equivalent (reference: python/paddle/optimizer/)."""
from .optimizer import Optimizer  # noqa: F401
from .optimizers import (  # noqa: F401
    SGD, Momentum, Adam, AdamW, Adamax, Adagrad, RMSProp, Lamb,
    LarsMomentum, Adadelta, Ftrl,
)
from . import lr  # noqa: F401
