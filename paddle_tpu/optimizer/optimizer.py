"""Optimizer base.

Reference parity: python/paddle/optimizer/optimizer.py (Optimizer.step /
minimize / clear_grad, accumulator management) with the reference design
point that the update IS an op and optimizer state tensors are framework
Variables (reference: paddle/fluid/operators/optimizers/*). Here each
optimizer's update rule is one fused jax op per parameter; state moments
are state Tensors so compiled training steps thread them functionally.

The learning rate is a state Tensor (not a python float) so LR schedules
don't force recompilation of traced steps: scheduler.step() mutates the
tensor outside the trace.
"""
import jax.numpy as jnp

from .. import profiler as _profiler
from ..core.tensor import Tensor
from ..core.dispatch import no_grad
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        if parameters is None:
            from ..static import _static_mode
            if not _static_mode[0]:
                raise ValueError(
                    "parameters must be given in dygraph mode (pass "
                    "model.parameters())")
            # static mode: parameters come from the program at minimize()
            parameters = []
        self._param_groups = list(parameters)
        self._grad_clip = grad_clip
        self._name = name
        self._accumulators = {}
        if isinstance(weight_decay, (int, float)):
            self._weight_decay = float(weight_decay)
            self._decay_mode = "l2"  # L2Decay: grad += wd * param
        elif weight_decay is None:
            self._weight_decay = 0.0
            self._decay_mode = "none"
        else:  # regularizer object (paddle.regularizer.L1Decay/L2Decay)
            self._weight_decay = float(getattr(weight_decay, "_coeff",
                                               getattr(weight_decay,
                                                       "coeff", 0.0)))
            self._decay_mode = getattr(weight_decay, "_mode", "l2")
            if self._decay_mode == "l1":
                # L1 is applied as a grad pre-transform in step(); the
                # update kernels' wd slot implements L2 only
                self._l1_coeff = self._weight_decay
                self._weight_decay = 0.0
        if isinstance(learning_rate, LRScheduler):
            self._lr_scheduler = learning_rate
            lr0 = learning_rate()
        else:
            self._lr_scheduler = None
            lr0 = float(learning_rate)
        self._lr_tensor = Tensor(jnp.asarray(lr0, jnp.float32),
                                 name="learning_rate", persistable=True)
        if self._lr_scheduler is not None:
            self._lr_scheduler._bind(self._lr_tensor)

    # -- public API --------------------------------------------------------
    def get_lr(self):
        return float(self._lr_tensor.numpy())

    def set_lr(self, value):
        self._lr_tensor.value = jnp.asarray(float(value), jnp.float32)

    def _parameter_list(self):
        params = []
        for g in self._param_groups:
            if isinstance(g, dict):
                params.extend(g["params"])
            else:
                params.append(g)
        return params

    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list():
            p.clear_grad()
        # step-boundary hint for the lazy micro-tracer: flushing here
        # makes each eager train step one stable (cache-hitting) fused
        # executable instead of drifting budget-boundary graphs
        from ..core import lazy as _lazy
        _lazy.flush()

    clear_gradients = clear_grad

    @no_grad()
    def step(self):
        # the optimizer/step scope shows up in the XLA trace, the
        # chrome host timeline and the registry span counters (see
        # paddle_tpu.observability) — the training-loop counterpart of
        # the serving engine's serving/* scopes
        with _profiler.record_scope("optimizer/step"):
            self._step_impl()

    def _step_impl(self):
        params_grads = [(p, p._grad) for p in self._parameter_list()
                        if p._grad is not None and p.trainable]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        if self._decay_mode == "l1" and getattr(self, "_l1_coeff", 0.0):
            # reference order: clip first, then append_regularization_ops;
            # L1Decay adds coeff * sign(param) to the clipped gradient
            # (L2 is applied inside the update kernels, also post-clip)
            coeff = self._l1_coeff
            params_grads = [
                (p, Tensor(g.value + coeff * jnp.sign(
                    p.value.astype(g.value.dtype))))
                for p, g in params_grads]
        from ..core.sparse_grad import SparseGradTensor
        for p, g in params_grads:
            if isinstance(g, SparseGradTensor) and g.is_sparse():
                # SelectedRows-equivalent path: update only touched rows
                # (reference: optimizers/*_op.h SelectedRows kernels)
                self._apply_sparse(p, g.slices.coalesce())
            else:
                self._apply_one(p, g)

    def _apply_sparse(self, p, slices):
        """Fallback for optimizers without a sparse kernel: densify."""
        self._apply_one(p, Tensor(slices.to_dense()))

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static.program import Variable
        if isinstance(loss, Variable):
            return self._minimize_static(loss, parameters)
        loss.backward()
        self.step()
        return None, None

    def _minimize_static(self, loss, parameters=None):
        """Static-graph minimize (reference: Optimizer.minimize building
        grad + update ops into the program, optimizer.py:1037): appends
        the gradient boundary, then records each update by running the
        normal _apply_one under the program-building hooks (the op call
        records, `p.value = new_p.value` records a write-back)."""
        prog = loss.program
        params = parameters
        if params is None:
            params = self._parameter_list() or [
                t for t in prog.persist.values()
                if getattr(t, "trainable", True) and not t.stop_gradient]
        params_grads = prog.append_backward(loss, params)
        if self._grad_clip is not None:
            # clip ops record into the program like any others
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            self._apply_one(p, g)
        return None, params_grads

    # -- state -------------------------------------------------------------
    def _acc(self, kind, param, init=None, shape=None, dtype=None):
        store = self._accumulators.setdefault(kind, {})
        key = id(param)
        if key not in store:
            if init is None:
                v = jnp.zeros(shape if shape is not None
                              else tuple(param.aval_shape()),
                              dtype or param._value.dtype
                              if param._value is not None else jnp.float32)
            elif callable(init):
                # callables defer the init array's construction to the
                # one call that actually creates the accumulator —
                # `init=jnp.ones(...)` at a per-step call site would
                # launch a device op every step
                v = init()
            else:
                v = init
            store[key] = Tensor(v, name=f"{param.name}_{kind}",
                                persistable=True)
        return store[key]

    def state_dict(self):
        sd = {}
        params = self._parameter_list()
        id_to_name = {id(p): p.name for p in params}
        for kind, store in self._accumulators.items():
            for pid, t in store.items():
                pname = id_to_name.get(pid, str(pid))
                sd[f"{pname}_{kind}"] = t
        sd["LR_Scheduler"] = {"last_lr": self.get_lr()}
        if self._lr_scheduler is not None:
            sd["LR_Scheduler"].update(self._lr_scheduler.state_dict())
        # auto param names (param_N) are NOT structure-stable across
        # fresh model instances; record the save-time parameter order
        # (inside the existing metadata entry, so consumers iterating
        # tensor values keep their `k != "LR_Scheduler"` filter) for
        # positional restore into renamed params
        sd["LR_Scheduler"]["param_order"] = [p.name for p in params]
        return sd

    def set_state_dict(self, state_dict):
        params = self._parameter_list()
        # prefer matching by the CURRENT params' own names (correct
        # under reordered parameter lists and rejects foreign
        # checkpoints); fall back to save-order positional mapping only
        # when no key matches — the fresh-instance case where auto
        # names (param_N) were re-numbered
        cur_names = sorted((p.name for p in params), key=len,
                           reverse=True)
        acc_keys = [k for k in state_dict if k != "LR_Scheduler"]
        name_hits = sum(
            1 for k in acc_keys
            if any(k.startswith(n + "_") for n in cur_names))
        saved_order = state_dict.get("LR_Scheduler", {}) \
            .get("param_order") if isinstance(
                state_dict.get("LR_Scheduler"), dict) else None
        if name_hits == 0 and saved_order is not None \
                and len(saved_order) == len(params):
            by_len = sorted(((saved, id(params[i]))
                             for i, saved in enumerate(saved_order)),
                            key=lambda kv: -len(kv[0]))
        else:
            # longest-name-first so a param name that prefixes
            # another's cannot steal the longer param's accumulator
            by_len = sorted(((p.name, id(p)) for p in params),
                            key=lambda kv: -len(kv[0]))
        for key, val in state_dict.items():
            if key == "LR_Scheduler":
                if self._lr_scheduler is not None and "last_epoch" in val:
                    self._lr_scheduler.last_epoch = val["last_epoch"]
                if "last_lr" in val:
                    self.set_lr(val["last_lr"])
                continue
            for pname, pid in by_len:
                if key.startswith(pname + "_"):
                    kind = key[len(pname) + 1:]
                    store = self._accumulators.setdefault(kind, {})
                    arr = val.value if isinstance(val, Tensor) else jnp.asarray(val)
                    if pid in store:
                        store[pid].value = arr
                    else:
                        store[pid] = Tensor(arr, persistable=True)
                    break

    # -- to be implemented by subclasses -----------------------------------
    def _apply_one(self, param, grad):
        raise NotImplementedError


class WrappedOptimizer:
    """Base for optimizer-wrapping transforms (meta-optimizers, ASP
    sparsity guarantee): delegates everything to the inner optimizer via
    __getattr__; subclasses override step()."""

    def __init__(self, inner_opt):
        self._inner_opt = inner_opt

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self, set_to_zero=False):
        self._inner_opt.clear_grad(set_to_zero)

    clear_gradients = clear_grad
