"""Concrete optimizers: SGD, Momentum, Adam, AdamW, Adamax, Adagrad,
RMSProp, Lamb.

Reference parity: paddle/fluid/operators/optimizers/{sgd,momentum,adam,
adamw,adamax,adagrad,rmsprop,lamb}_op and python/paddle/optimizer/*.py.
Each update rule is one fused jax op (XLA fuses the whole elementwise
chain into a single kernel per parameter — the analogue of the reference's
fused CUDA optimizer kernels).
"""
import jax
import jax.numpy as jnp

from ..core.dispatch import register_op
from .optimizer import Optimizer


@register_op("sgd_update", differentiable=False)
def _sgd(param, grad, lr, *, wd):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd:
        g = g + wd * p32
    new_p = p32 - lr * g
    return new_p.astype(param.dtype)


@register_op("momentum_update", differentiable=False)
def _momentum(param, grad, velocity, lr, *, mu, wd, use_nesterov):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd:
        g = g + wd * p32
    v_new = mu * velocity + g
    if use_nesterov:
        new_p = p32 - lr * (g + mu * v_new)
    else:
        new_p = p32 - lr * v_new
    return new_p.astype(param.dtype), v_new


# ---- sparse (SelectedRows-equivalent) row updates --------------------------
# Reference: operators/optimizers/sgd_op.h (SelectedRows branch),
# momentum_op.h SparseMomentumFunctor, adam_op.h SparseAdamFunctor
# (lazy_mode). Only the looked-up rows are read and written; XLA lowers
# the gather/scatter pair to O(rows * dim) work.

@register_op("sgd_sparse_update", differentiable=False)
def _sgd_sparse(param, idx, vals, lr, *, wd):
    p_rows = jnp.take(param, idx, axis=0).astype(jnp.float32)
    g = vals.astype(jnp.float32)
    if wd:
        g = g + wd * p_rows
    new_rows = p_rows - lr * g
    return param.at[idx].set(new_rows.astype(param.dtype))


@register_op("momentum_sparse_update", differentiable=False)
def _momentum_sparse(param, idx, vals, velocity, lr, *, mu, wd,
                     use_nesterov):
    # dense-equivalent semantics (reference SparseMomentumFunctor treats
    # rows absent from the grad as grad=0): velocity decays everywhere
    # and untouched params keep moving — only the grad itself is sparse
    p32 = param.astype(jnp.float32)
    g = jnp.zeros_like(p32).at[idx].add(vals.astype(jnp.float32))
    if wd:
        g = g + wd * p32
    v_new = mu * velocity + g
    upd = g + mu * v_new if use_nesterov else v_new
    return (p32 - lr * upd).astype(param.dtype), v_new


@register_op("adam_sparse_update", differentiable=False)
def _adam_sparse(param, idx, vals, m, v, beta1_pow, beta2_pow, lr, *,
                 beta1, beta2, epsilon, wd, decoupled, lazy):
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    if lazy:
        # lazy_mode (reference adam_op.h SparseAdamFunctor, lazy): ONLY
        # looked-up rows of param/moments change — O(rows) work
        g = vals.astype(jnp.float32)
        p_rows = jnp.take(param, idx, axis=0).astype(jnp.float32)
        if wd and not decoupled:
            g = g + wd * p_rows
        m_rows = beta1 * jnp.take(m, idx, axis=0) + (1.0 - beta1) * g
        v_rows = beta2 * jnp.take(v, idx, axis=0) + (1.0 - beta2) * g * g
        m_hat = m_rows / (1.0 - b1p)
        v_hat = v_rows / (1.0 - b2p)
        upd = lr * m_hat / (jnp.sqrt(v_hat) + epsilon)
        if wd and decoupled:
            upd = upd + lr * wd * p_rows
        return (param.at[idx].set((p_rows - upd).astype(param.dtype)),
                m.at[idx].set(m_rows), v.at[idx].set(v_rows), b1p, b2p)
    # lazy_mode=False (default): dense-equivalent — absent rows see
    # grad=0, so their moments decay and params keep moving, matching
    # the dense trajectory exactly; the grad stays sparse (the scatter
    # fuses into the elementwise chain, no dense grad is stored)
    p32 = param.astype(jnp.float32)
    g = jnp.zeros_like(p32).at[idx].add(vals.astype(jnp.float32))
    if wd and not decoupled:
        g = g + wd * p32
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    m_hat = m_new / (1.0 - b1p)
    v_hat = v_new / (1.0 - b2p)
    upd = m_hat / (jnp.sqrt(v_hat) + epsilon)
    if wd and decoupled:
        upd = upd + wd * p32
    return ((p32 - lr * upd).astype(param.dtype), m_new, v_new, b1p, b2p)


@register_op("adam_update", differentiable=False)
def _adam(param, grad, m, v, beta1_pow, beta2_pow, lr, *,
          beta1, beta2, epsilon, wd, decoupled, lazy):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd and not decoupled:
        g = g + wd * p32
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    m_hat = m_new / (1.0 - b1p)
    v_hat = v_new / (1.0 - b2p)
    update = m_hat / (jnp.sqrt(v_hat) + epsilon)
    if wd and decoupled:
        update = update + wd * p32
    new_p = p32 - lr * update
    return new_p.astype(param.dtype), m_new, v_new, b1p, b2p


@register_op("adamax_update", differentiable=False)
def _adamax(param, grad, m, inf_norm, beta1_pow, lr, *,
            beta1, beta2, epsilon, wd):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd:
        g = g + wd * p32
    m_new = beta1 * m + (1.0 - beta1) * g
    u_new = jnp.maximum(beta2 * inf_norm, jnp.abs(g))
    b1p = beta1_pow * beta1
    new_p = p32 - (lr / (1.0 - b1p)) * m_new / (u_new + epsilon)
    return new_p.astype(param.dtype), m_new, u_new, b1p


@register_op("adagrad_update", differentiable=False)
def _adagrad(param, grad, moment, lr, *, epsilon, wd):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd:
        g = g + wd * p32
    mom_new = moment + g * g
    new_p = p32 - lr * g / (jnp.sqrt(mom_new) + epsilon)
    return new_p.astype(param.dtype), mom_new


@register_op("rmsprop_update", differentiable=False)
def _rmsprop(param, grad, mean_square, mean_grad, moment, lr, *,
             rho, epsilon, momentum, centered, wd):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    if wd:
        g = g + wd * p32
    ms_new = rho * mean_square + (1.0 - rho) * g * g
    if centered:
        mg_new = rho * mean_grad + (1.0 - rho) * g
        denom = jnp.sqrt(ms_new - mg_new * mg_new + epsilon)
    else:
        mg_new = mean_grad
        denom = jnp.sqrt(ms_new + epsilon)
    mom_new = momentum * moment + lr * g / denom
    new_p = p32 - mom_new
    return new_p.astype(param.dtype), ms_new, mg_new, mom_new


@register_op("lamb_update", differentiable=False)
def _lamb(param, grad, m, v, beta1_pow, beta2_pow, lr, *,
          beta1, beta2, epsilon, wd):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    b1p = beta1_pow * beta1
    b2p = beta2_pow * beta2
    m_hat = m_new / (1.0 - b1p)
    v_hat = v_new / (1.0 - b2p)
    r = m_hat / (jnp.sqrt(v_hat) + epsilon) + wd * p32
    w_norm = jnp.sqrt(jnp.sum(p32 * p32))
    r_norm = jnp.sqrt(jnp.sum(r * r))
    trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
    new_p = p32 - lr * trust * r
    return new_p.astype(param.dtype), m_new, v_new, b1p, b2p


class SGD(Optimizer):
    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)

    def _apply_one(self, p, g):
        new_p = _sgd(p, g, self._lr_tensor, wd=self._weight_decay)
        p.value = new_p.value

    def _apply_sparse(self, p, slices):
        new_p = _sgd_sparse(p, slices.indices, slices.values,
                            self._lr_tensor, wd=self._weight_decay)
        p.value = new_p.value


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._momentum = float(momentum)
        self._use_nesterov = bool(use_nesterov)

    def _apply_one(self, p, g):
        vel = self._acc("velocity", p, shape=tuple(p.aval_shape()),
                        dtype=jnp.float32)
        new_p, new_v = _momentum(p, g, vel, self._lr_tensor,
                                 mu=self._momentum, wd=self._weight_decay,
                                 use_nesterov=self._use_nesterov)
        p.value = new_p.value
        vel.value = new_v.value

    def _apply_sparse(self, p, slices):
        vel = self._acc("velocity", p, shape=tuple(p.aval_shape()),
                        dtype=jnp.float32)
        new_p, new_v = _momentum_sparse(
            p, slices.indices, slices.values, vel, self._lr_tensor,
            mu=self._momentum, wd=self._weight_decay,
            use_nesterov=self._use_nesterov)
        p.value = new_p.value
        vel.value = new_v.value


class Adam(Optimizer):
    _decoupled = False

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1 = float(beta1)
        self._beta2 = float(beta2)
        self._epsilon = float(epsilon)
        self._lazy_mode = bool(lazy_mode)

    def _apply_one(self, p, g):
        shape = tuple(p.aval_shape())
        m = self._acc("moment1", p, shape=shape, dtype=jnp.float32)
        v = self._acc("moment2", p, shape=shape, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=lambda: jnp.ones((), jnp.float32))
        new_p, m_n, v_n, b1n, b2n = _adam(
            p, g, m, v, b1p, b2p, self._lr_tensor,
            beta1=self._beta1, beta2=self._beta2, epsilon=self._epsilon,
            wd=self._weight_decay, decoupled=self._decoupled, lazy=False)
        p.value = new_p.value
        m.value = m_n.value
        v.value = v_n.value
        b1p.value = b1n.value
        b2p.value = b2n.value

    def _apply_sparse(self, p, slices):
        """lazy_mode sparse Adam: only looked-up rows of param/moments are
        updated (reference: adam_op.h SparseAdamFunctor, lazy_mode)."""
        shape = tuple(p.aval_shape())
        m = self._acc("moment1", p, shape=shape, dtype=jnp.float32)
        v = self._acc("moment2", p, shape=shape, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=lambda: jnp.ones((), jnp.float32))
        new_p, m_n, v_n, b1n, b2n = _adam_sparse(
            p, slices.indices, slices.values, m, v, b1p, b2p,
            self._lr_tensor, beta1=self._beta1, beta2=self._beta2,
            epsilon=self._epsilon, wd=self._weight_decay,
            decoupled=self._decoupled, lazy=self._lazy_mode)
        p.value = new_p.value
        m.value = m_n.value
        v.value = v_n.value
        b1p.value = b1n.value
        b2p.value = b2n.value


class AdamW(Adam):
    """Decoupled weight decay (reference: operators/optimizers/adamw_op)."""
    _decoupled = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=0.01,
                 grad_clip=None, lr_ratio=None, apply_decay_param_fun=None,
                 lazy_mode=False, multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay if weight_decay else None, grad_clip,
                         lazy_mode, multi_precision, name)
        self._weight_decay = float(weight_decay or 0.0)
        self._decay_mode = "decoupled"
        self._apply_decay_param_fun = apply_decay_param_fun

    def _apply_one(self, p, g):
        wd_save = self._weight_decay
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            self._weight_decay = 0.0
        try:
            super()._apply_one(p, g)
        finally:
            self._weight_decay = wd_save

    def _apply_sparse(self, p, slices):
        wd_save = self._weight_decay
        if (self._apply_decay_param_fun is not None
                and not self._apply_decay_param_fun(p.name)):
            self._weight_decay = 0.0
        try:
            super()._apply_sparse(p, slices)
        finally:
            self._weight_decay = wd_save


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2 = float(beta1), float(beta2)
        self._epsilon = float(epsilon)

    def _apply_one(self, p, g):
        shape = tuple(p.aval_shape())
        m = self._acc("moment", p, shape=shape, dtype=jnp.float32)
        u = self._acc("inf_norm", p, shape=shape, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.ones((), jnp.float32))
        new_p, m_n, u_n, b1n = _adamax(
            p, g, m, u, b1p, self._lr_tensor, beta1=self._beta1,
            beta2=self._beta2, epsilon=self._epsilon, wd=self._weight_decay)
        p.value = new_p.value
        m.value = m_n.value
        u.value = u_n.value
        b1p.value = b1n.value


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None,
                 initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = float(epsilon)
        self._init_acc = float(initial_accumulator_value)

    def _apply_one(self, p, g):
        mom = self._acc("moment", p,
                        init=lambda: jnp.full(tuple(p.aval_shape()), self._init_acc,
                                      jnp.float32))
        new_p, mom_n = _adagrad(p, g, mom, self._lr_tensor,
                                epsilon=self._epsilon, wd=self._weight_decay)
        p.value = new_p.value
        mom.value = mom_n.value


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = float(rho), float(epsilon)
        self._momentum, self._centered = float(momentum), bool(centered)

    def _apply_one(self, p, g):
        shape = tuple(p.aval_shape())
        ms = self._acc("mean_square", p, shape=shape, dtype=jnp.float32)
        mg = self._acc("mean_grad", p, shape=shape, dtype=jnp.float32)
        mom = self._acc("momentum_acc", p, shape=shape, dtype=jnp.float32)
        new_p, ms_n, mg_n, mom_n = _rmsprop(
            p, g, ms, mg, mom, self._lr_tensor, rho=self._rho,
            epsilon=self._epsilon, momentum=self._momentum,
            centered=self._centered, wd=self._weight_decay)
        p.value = new_p.value
        ms.value = ms_n.value
        mg.value = mg_n.value
        mom.value = mom_n.value


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-6, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2 = float(beta1), float(beta2)
        self._epsilon = float(epsilon)
        self._lamb_wd = float(lamb_weight_decay)
        self._exclude_fn = exclude_from_weight_decay_fn

    def _apply_one(self, p, g):
        shape = tuple(p.aval_shape())
        m = self._acc("moment1", p, shape=shape, dtype=jnp.float32)
        v = self._acc("moment2", p, shape=shape, dtype=jnp.float32)
        b1p = self._acc("beta1_pow", p, init=lambda: jnp.ones((), jnp.float32))
        b2p = self._acc("beta2_pow", p, init=lambda: jnp.ones((), jnp.float32))
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(p):
            wd = 0.0
        new_p, m_n, v_n, b1n, b2n = _lamb(
            p, g, m, v, b1p, b2p, self._lr_tensor, beta1=self._beta1,
            beta2=self._beta2, epsilon=self._epsilon, wd=wd)
        p.value = new_p.value
        m.value = m_n.value
        v.value = v_n.value
        b1p.value = b1n.value
        b2p.value = b2n.value


@register_op("lars_update", differentiable=False)
def _lars(param, grad, velocity, lr, *, mu, lars_coeff, wd, epsilon):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    p_norm = jnp.sqrt(jnp.sum(p32 * p32))
    g_norm = jnp.sqrt(jnp.sum(g * g))
    local_lr = jnp.where(
        (p_norm > 0) & (g_norm > 0),
        lars_coeff * p_norm / (g_norm + wd * p_norm + epsilon), 1.0)
    v_new = mu * velocity + lr * local_lr * (g + wd * p32)
    new_p = p32 - v_new
    return new_p.astype(param.dtype), v_new


class LarsMomentum(Optimizer):
    """Layer-wise adaptive rate scaling (reference:
    operators/optimizers/lars_momentum_op.cc + fleet lars_optimizer.py)."""

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005,
                 parameters=None, grad_clip=None, epsilon=1e-9, name=None,
                 exclude_from_weight_decay=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._momentum = float(momentum)
        self._lars_coeff = float(lars_coeff)
        self._lars_wd = float(lars_weight_decay)
        self._epsilon = float(epsilon)
        self._exclude = exclude_from_weight_decay or []

    def _apply_one(self, p, g):
        vel = self._acc("velocity", p, shape=tuple(p.aval_shape()),
                        dtype=jnp.float32)
        wd = self._lars_wd
        if any(tag in p.name for tag in self._exclude):
            wd = 0.0
        new_p, new_v = _lars(p, g, vel, self._lr_tensor, mu=self._momentum,
                             lars_coeff=self._lars_coeff, wd=wd,
                             epsilon=self._epsilon)
        p.value = new_p.value
        vel.value = new_v.value


@register_op("adadelta_update", differentiable=False)
def _adadelta(param, grad, avg_sq_grad, avg_sq_update, *, rho, epsilon):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    asg_new = rho * avg_sq_grad + (1.0 - rho) * g * g
    update = -jnp.sqrt((avg_sq_update + epsilon) / (asg_new + epsilon)) * g
    asu_new = rho * avg_sq_update + (1.0 - rho) * update * update
    new_p = p32 + update
    return new_p.astype(param.dtype), asg_new, asu_new


@register_op("ftrl_update", differentiable=False)
def _ftrl(param, grad, sq_accum, lin_accum, lr, *, l1, l2, lr_power):
    g = grad.astype(jnp.float32)
    p32 = param.astype(jnp.float32)
    new_accum = sq_accum + g * g
    if lr_power == -0.5:
        lin_new = lin_accum + g - (jnp.sqrt(new_accum)
                                   - jnp.sqrt(sq_accum)) / lr * p32
        y = jnp.sqrt(new_accum) / lr + 2.0 * l2
    else:
        lin_new = lin_accum + g - (new_accum ** (-lr_power)
                                   - sq_accum ** (-lr_power)) / lr * p32
        y = new_accum ** (-lr_power) / lr + 2.0 * l2
    x = l1 * jnp.sign(lin_new) - lin_new
    pre_shrink = x / y
    new_p = jnp.where(jnp.abs(lin_new) > l1, pre_shrink, 0.0)
    return new_p.astype(param.dtype), new_accum, lin_new


class Adadelta(Optimizer):
    """Reference: operators/optimizers/adadelta_op.h (update has no LR
    factor — param += update directly) + python/paddle/optimizer/adadelta.py."""

    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho, self._epsilon = float(rho), float(epsilon)

    def _apply_one(self, p, g):
        shape = tuple(p.aval_shape())
        asg = self._acc("avg_squared_grad", p, shape=shape, dtype=jnp.float32)
        asu = self._acc("avg_squared_update", p, shape=shape,
                        dtype=jnp.float32)
        if self._weight_decay:
            g = g + self._weight_decay * p
        new_p, asg_n, asu_n = _adadelta(p, g, asg, asu, rho=self._rho,
                                        epsilon=self._epsilon)
        p.value = new_p.value
        asg.value = asg_n.value
        asu.value = asu_n.value


class Ftrl(Optimizer):
    """Follow-the-regularized-leader (reference:
    operators/optimizers/ftrl_op.h)."""

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._l1 = float(l1) + 1e-10  # reference op adds epsilon to avoid 0
        self._l2 = float(l2) + 1e-10
        self._lr_power = float(lr_power)

    def _apply_one(self, p, g):
        shape = tuple(p.aval_shape())
        sq = self._acc("squared_accum", p, shape=shape, dtype=jnp.float32)
        lin = self._acc("linear_accum", p, shape=shape, dtype=jnp.float32)
        new_p, sq_n, lin_n = _ftrl(p, g, sq, lin, self._lr_tensor,
                                   l1=self._l1, l2=self._l2,
                                   lr_power=self._lr_power)
        p.value = new_p.value
        sq.value = sq_n.value
        lin.value = lin_n.value
