"""Learning-rate schedulers.

Reference parity: python/paddle/optimizer/lr.py. Schedulers compute a
python float per step/epoch and write it into the optimizer's LR state
Tensor, so traced training steps never recompile on LR change.
"""
import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = float(learning_rate)
        self.last_epoch = last_epoch
        self.verbose = verbose
        self._lr_tensor = None
        self.step()

    def _bind(self, lr_tensor):
        self._lr_tensor = lr_tensor
        self._sync()

    def _sync(self):
        if self._lr_tensor is not None:
            self._lr_tensor.value = jnp.asarray(self.last_lr, jnp.float32)

    def __call__(self):
        return self.last_lr

    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = self.get_lr()
        self._sync()

    def get_lr(self):
        raise NotImplementedError

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)
        self._sync()

    set_dict = set_state_dict


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0,
                 last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = max(self.last_epoch, 1)
        a = step ** -0.5
        b = self.warmup_steps ** -1.5 * step
        return self.base_lr * (self.d_model ** -0.5) * min(a, b)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = boundaries
        self.values = values
        super().__init__(values[0], last_epoch, verbose)

    def get_lr(self):
        for i, b in enumerate(self.boundaries):
            if self.last_epoch < b:
                return self.values[i]
        return self.values[len(self.boundaries)]


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * math.exp(-self.gamma * self.last_epoch)


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr / (1 + self.gamma * self.last_epoch)


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        step = self.last_epoch
        if self.cycle:
            div = math.ceil(step / self.decay_steps) if step > 0 else 1
            decay_steps = self.decay_steps * div
        else:
            decay_steps = self.decay_steps
            step = min(step, self.decay_steps)
        return (self.base_lr - self.end_lr) * \
            (1 - step / decay_steps) ** self.power + self.end_lr


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** self.last_epoch


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.milestones = milestones
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        n = sum(1 for m in self.milestones if self.last_epoch >= m)
        return self.base_lr * self.gamma ** n


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1,
                 verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.gamma ** (self.last_epoch // self.step_size)


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.base_lr * self.lr_lambda(self.last_epoch)


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1,
                 verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def get_lr(self):
        return self.eta_min + (self.base_lr - self.eta_min) * \
            (1 + math.cos(math.pi * self.last_epoch / self.T_max)) / 2


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr,
                 last_epoch=-1, verbose=False):
        self.lr = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(start_lr, last_epoch, verbose)

    def get_lr(self):
        if self.last_epoch < self.warmup_steps:
            return (self.end_lr - self.start_lr) * \
                self.last_epoch / self.warmup_steps + self.start_lr
        if isinstance(self.lr, LRScheduler):
            self.lr.step()
            return self.lr.last_lr
        return float(self.lr)


class ReduceOnPlateau(LRScheduler):
    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad = 0
        self.cooldown_counter = 0
        super().__init__(learning_rate, -1, verbose)

    def get_lr(self):
        return getattr(self, "last_lr", self.base_lr)

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            if not hasattr(self, "last_lr"):
                self.last_lr = self.base_lr
            self._sync()
            return
        from ..core.tensor import Tensor
        cur = float(metrics.numpy()) if isinstance(metrics, Tensor) else float(metrics)
        if self.best is None:
            improved = True
        elif self.mode == "min":
            thr = self.best * (1 - self.threshold) if self.threshold_mode == "rel" \
                else self.best - self.threshold
            improved = cur < thr
        else:
            thr = self.best * (1 + self.threshold) if self.threshold_mode == "rel" \
                else self.best + self.threshold
            improved = cur > thr
        if improved:
            self.best = cur
            self.num_bad = 0
        else:
            self.num_bad += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad = 0
        if self.num_bad > self.patience:
            new_lr = max(self.last_lr * self.factor, self.min_lr)
            if self.last_lr - new_lr > 1e-10:
                self.last_lr = new_lr
            self.cooldown_counter = self.cooldown
            self.num_bad = 0
        self._sync()
