"""Runtime lock patrol: lockdep-style deadlock and held-across-dispatch lint.

``LockPatrol`` wraps every ``threading.Lock`` / ``RLock`` / ``Condition``
created inside ``paddle_tpu.*`` with a site-attributed proxy (creation
file:line is the lock's identity) and records the acquired-while-holding
edge graph across all threads.  A cycle in the merged graph is a
``LockOrderFinding`` naming every lock site on the cycle plus the
acquisition stack that created each edge.  Separately, ``note_blocking``
hooks (armed in the engine's timed AOT dispatch path and in the blocking
socket primitives) flag any patrolled lock held while control enters a
dispatch or a blocking socket call — the PR-9 pause class, where a slow
peer wedges the step loop through a lock.

Gating mirrors ``birth.py``: off by default, refcounted
``enable_patrol()`` / ``disable_patrol()``, a ``lock_patrol()`` context
manager, and ``PADDLE_TPU_ANALYSIS=1`` arming at import.  When off, no
factory is patched and the only residual cost in the engine hot path is a
single module-global ``is not None``/boolean test.
"""

from __future__ import annotations

import contextlib
import dataclasses
import os
import socket as _socket_mod
import sys
import threading
import traceback

from .lint import Finding, register_lint_pass

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_THIS_FILE = os.path.abspath(__file__)

# Real factories, captured before any patching so nested enables and the
# patrol's own bookkeeping always use unproxied primitives.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock
_REAL_CONDITION = threading.Condition

_SOCKET_METHODS = ("connect", "recv", "recv_into", "sendall", "send", "accept")

# Fast-path flag read by the engine dispatch hook; True only while armed.
_armed = False
_state = None
_refs = 0
_master = _REAL_LOCK()
_tls = threading.local()

# (site_substring, blocking_kind, justification) triples: a patrolled lock
# whose site contains the substring is allowed to be held across blocking
# calls of that kind.  Kept tiny and justified inline so it rots loudly.
DEFAULT_PATROL_ALLOW = (
    (
        "transport.py",
        "aot_dispatch",
        "EngineGateway._lock serializes submissions with the step loop by "
        "design: _drive() holds it across engine.step() so POST handlers "
        "observe a consistent engine; no socket I/O ever happens under it.",
    ),
)


@dataclasses.dataclass
class LockOrderFinding(Finding):
    """A cycle in the merged acquired-while-holding graph."""

    locks: tuple = ()
    stacks: tuple = ()

    def to_dict(self):
        d = super().to_dict()
        d["locks"] = list(self.locks)
        d["stacks"] = list(self.stacks)
        return d


@dataclasses.dataclass
class HeldAcrossFinding(Finding):
    """A patrolled lock held across a dispatch or blocking socket call."""

    lock_site: str = ""
    blocking_kind: str = ""
    blocking_label: str = ""
    blocked_at: str = ""
    stack: str = ""

    def to_dict(self):
        d = super().to_dict()
        d["lock_site"] = self.lock_site
        d["blocking_kind"] = self.blocking_kind
        d["blocking_label"] = self.blocking_label
        d["blocked_at"] = self.blocked_at
        d["stack"] = self.stack
        return d


class _PatrolState:
    def __init__(self, paths, allow):
        self.paths = tuple(os.path.abspath(p) for p in paths)
        self.allow = tuple(allow)
        self.nlocks = 0
        self.acquires = 0
        # (a_site, b_site) -> {"thread": name, "stack": str}
        self.edges = {}
        # a_site -> set of b_sites acquired while a held
        self.adj = {}
        self.findings = []
        self._seen_cycles = set()
        self._seen_held = set()


def _held():
    h = getattr(_tls, "held", None)
    if h is None:
        h = []
        _tls.held = h
    return h


def _stack(skip=2):
    return "".join(traceback.format_stack(sys._getframe(skip)))


def _find_path(adj, start, goal):
    """Iterative DFS: a path start -> ... -> goal in adj, or None."""
    stack = [(start, [start])]
    visited = set()
    while stack:
        node, path = stack.pop()
        if node == goal:
            return path
        if node in visited:
            continue
        visited.add(node)
        for nxt in adj.get(node, ()):
            stack.append((nxt, path + [nxt]))
    return None


def _add_edge(st, a_site, b_site, stack_txt, thread_name):
    if (a_site, b_site) in st.edges:
        return
    st.edges[(a_site, b_site)] = {"thread": thread_name, "stack": stack_txt}
    st.adj.setdefault(a_site, set()).add(b_site)
    # New edge a->b closes a cycle iff a path b -> ... -> a already exists.
    back = _find_path(st.adj, b_site, a_site)
    if back is None:
        return
    cycle_sites = back  # b, ..., a ; new edge a->b closes it
    key = frozenset(cycle_sites)
    if key in st._seen_cycles:
        return
    st._seen_cycles.add(key)
    edge_pairs = list(zip(cycle_sites, cycle_sites[1:])) + [(a_site, b_site)]
    stacks = tuple(
        "acquired %s while holding %s [thread %s]\n%s"
        % (b, a, st.edges[(a, b)]["thread"], st.edges[(a, b)]["stack"])
        for a, b in edge_pairs
        if (a, b) in st.edges
    )
    st.findings.append(
        LockOrderFinding(
            pass_name="lock-order",
            severity="error",
            site=a_site,
            detail="lock-order cycle: " + " -> ".join(cycle_sites + [b_site]),
            locks=tuple(dict.fromkeys(cycle_sites)),
            stacks=stacks,
        )
    )


def _note_attempt(proxy):
    """Record ordering edges at acquire *attempt*, lockdep-style.

    Recording on attempt (not success) is what lets the patrol name a
    cycle even while the deadlock it predicts is actually in flight —
    neither thread would ever complete its second acquire.
    """
    st = _state
    if st is None:
        return
    held = _held()
    if any(h is proxy for h in held):
        # RLock reentrancy: no new ordering information, no self-edges.
        return
    tname = threading.current_thread().name
    new_edges = []
    for h in held:
        if h.site != proxy.site and (h.site, proxy.site) not in st.edges:
            new_edges.append(h.site)
    stack_txt = _stack(3) if new_edges else ""
    with _master:
        st.acquires += 1
        for a_site in new_edges:
            _add_edge(st, a_site, proxy.site, stack_txt, tname)


def _note_release(proxy):
    if _state is None:
        return
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i] is proxy:
            del held[i]
            return


class _PatrolProxy:
    """Site-attributed wrapper around a real Lock/RLock."""

    __slots__ = ("_real", "site", "kind")

    def __init__(self, real, site, kind):
        self._real = real
        self.site = site
        self.kind = kind

    def acquire(self, blocking=True, timeout=-1):
        if _armed:
            _note_attempt(self)
        ok = self._real.acquire(blocking, timeout)
        if ok and _armed:
            _held().append(self)
        return ok

    def release(self):
        self._real.release()
        if _armed:
            _note_release(self)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._real.locked()

    def __repr__(self):
        return "<patrolled %s at %s>" % (self.kind, self.site)


class _PatrolCondition(_PatrolProxy):
    """Condition proxy: wait() releases the lock, so held-state must track."""

    __slots__ = ("_cond",)

    def __init__(self, cond, site):
        super().__init__(cond, site, "Condition")
        self._cond = cond

    def _pop_silent(self):
        held = _held()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is self:
                del held[i]
                return True
        return False

    def wait(self, timeout=None):
        was_held = _armed and self._pop_silent()
        try:
            return self._cond.wait(timeout)
        finally:
            if was_held:
                # Reacquisition on wakeup is a no-order event: the lock was
                # already ours before the wait; re-push without edges.
                _held().append(self)

    def wait_for(self, predicate, timeout=None):
        was_held = _armed and self._pop_silent()
        try:
            return self._cond.wait_for(predicate, timeout)
        finally:
            if was_held:
                _held().append(self)

    def notify(self, n=1):
        return self._cond.notify(n)

    def notify_all(self):
        return self._cond.notify_all()


def _creation_site(depth=2):
    """file:line of the caller, or None if outside the patrolled paths."""
    st = _state
    if st is None:
        return None
    try:
        frame = sys._getframe(depth)
    except ValueError:
        return None
    fn = frame.f_code.co_filename
    if not fn or fn.startswith("<"):
        return None
    afn = os.path.abspath(fn)
    if afn == _THIS_FILE:
        return None
    for p in st.paths:
        if afn.startswith(p):
            name = getattr(frame.f_code, "co_qualname", frame.f_code.co_name)
            parent = os.path.basename(os.path.dirname(afn))
            return "%s/%s:%d (%s)" % (
                parent,
                os.path.basename(afn),
                frame.f_lineno,
                name,
            )
    return None


def _patrol_lock():
    real = _REAL_LOCK()
    site = _creation_site()
    if site is None:
        return real
    st = _state
    if st is not None:
        with _master:
            st.nlocks += 1
    return _PatrolProxy(real, site, "Lock")


def _patrol_rlock():
    real = _REAL_RLOCK()
    site = _creation_site()
    if site is None:
        return real
    st = _state
    if st is not None:
        with _master:
            st.nlocks += 1
    return _PatrolProxy(real, site, "RLock")


def _patrol_condition(lock=None):
    site = _creation_site()
    if site is None:
        if lock is not None and isinstance(lock, _PatrolProxy):
            lock = lock._real
        return _REAL_CONDITION(lock)
    if lock is not None and isinstance(lock, _PatrolProxy):
        lock = lock._real
    cond = _REAL_CONDITION(lock)
    st = _state
    if st is not None:
        with _master:
            st.nlocks += 1
    return _PatrolCondition(cond, site)


def _blocking_site():
    """Innermost frame outside this module and the socket module."""
    f = sys._getframe(1)
    skip = (_THIS_FILE, os.path.abspath(_socket_mod.__file__))
    while f is not None:
        fn = f.f_code.co_filename
        if fn and not fn.startswith("<") and os.path.abspath(fn) not in skip:
            return "%s/%s:%d (%s)" % (
                os.path.basename(os.path.dirname(os.path.abspath(fn))),
                os.path.basename(fn),
                f.f_lineno,
                getattr(f.f_code, "co_qualname", f.f_code.co_name),
            )
        f = f.f_back
    return "<unknown>"


def note_blocking(kind, label=""):
    """Record that control is entering a blocking call of ``kind``.

    Called from the engine's timed AOT dispatch path (``kind="aot_dispatch"``)
    and from the patched blocking socket primitives (``kind="socket"``).
    Any patrolled lock currently held by this thread is a finding unless the
    patrol allowlist covers that (site, kind) pair.
    """
    st = _state
    if st is None:
        return
    held = _held()
    if not held:
        return
    blocked_at = _blocking_site()
    tname = threading.current_thread().name
    seen_proxies = set()
    for h in held:
        if id(h) in seen_proxies:
            continue
        seen_proxies.add(id(h))
        allowed = False
        for site_sub, allow_kind, _just in st.allow:
            if site_sub in h.site and allow_kind == kind:
                allowed = True
                break
        if allowed:
            continue
        key = (h.site, kind, blocked_at)
        with _master:
            if key in st._seen_held:
                continue
            st._seen_held.add(key)
            st.findings.append(
                HeldAcrossFinding(
                    pass_name="lock-held-across-dispatch",
                    severity="error",
                    site=h.site,
                    detail=(
                        "lock %s held while entering blocking %s (%s) at %s "
                        "[thread %s]" % (h.site, kind, label, blocked_at, tname)
                    ),
                    lock_site=h.site,
                    blocking_kind=kind,
                    blocking_label=label,
                    blocked_at=blocked_at,
                    stack=_stack(2),
                )
            )


def _wrap_socket_method(name):
    real = getattr(_socket_mod.socket, name)

    def wrapper(self, *args, **kwargs):
        if _armed and getattr(self, "gettimeout", None) is not None:
            # Nonblocking sockets (timeout 0) never wedge a holder.
            try:
                blocking = self.gettimeout() != 0
            except OSError:
                blocking = True
            if blocking:
                note_blocking("socket", name)
        return real(self, *args, **kwargs)

    wrapper.__name__ = name
    wrapper._patrol_wrapped = real
    return wrapper


_socket_saved = {}


def _install():
    threading.Lock = _patrol_lock
    threading.RLock = _patrol_rlock
    threading.Condition = _patrol_condition
    for name in _SOCKET_METHODS:
        had_own = name in _socket_mod.socket.__dict__
        _socket_saved[name] = (had_own, getattr(_socket_mod.socket, name))
        try:
            setattr(_socket_mod.socket, name, _wrap_socket_method(name))
        except (AttributeError, TypeError):
            _socket_saved.pop(name, None)


def _uninstall():
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    threading.Condition = _REAL_CONDITION
    for name, (had_own, orig) in list(_socket_saved.items()):
        try:
            if had_own:
                setattr(_socket_mod.socket, name, orig)
            else:
                delattr(_socket_mod.socket, name)
        except (AttributeError, TypeError):
            pass
    _socket_saved.clear()


class LockPatrol:
    """Read-only view over the active (or last) patrol state."""

    def __init__(self, state):
        self._st = state

    def findings(self):
        with _master:
            return list(self._st.findings)

    def report(self):
        with _master:
            return {
                "enabled": _state is self._st,
                "locks": self._st.nlocks,
                "edges": len(self._st.edges),
                "acquires": self._st.acquires,
                "findings": [f.to_dict() for f in self._st.findings],
            }


def enable_patrol(paths=None, allow=DEFAULT_PATROL_ALLOW):
    """Arm the lock patrol (refcounted). Returns a :class:`LockPatrol` view.

    ``paths``: directories whose lock creations are patrolled; defaults to
    the ``paddle_tpu`` package dir.  Nested enables share one state; only
    the outermost ``disable_patrol`` tears down.
    """
    global _armed, _state, _refs
    with _master:
        _refs += 1
        if _refs == 1:
            _state = _PatrolState(paths or (_PKG_DIR,), allow)
            _install()
            _armed = True
        return LockPatrol(_state)


def disable_patrol():
    """Disarm one level of patrol; outermost call restores the factories."""
    global _armed, _state, _refs
    with _master:
        if _refs == 0:
            return
        _refs -= 1
        if _refs == 0:
            _armed = False
            _uninstall()
            _state = None
            _tls.held = []


@contextlib.contextmanager
def lock_patrol(paths=None, allow=DEFAULT_PATROL_ALLOW):
    """Context manager: arm the patrol, yield the :class:`LockPatrol` view."""
    patrol = enable_patrol(paths=paths, allow=allow)
    try:
        yield patrol
    finally:
        disable_patrol()


def patrol_report():
    """Current patrol report; identical shape whether armed or not."""
    with _master:
        st = _state
        if st is None:
            return {
                "enabled": False,
                "locks": 0,
                "edges": 0,
                "acquires": 0,
                "findings": [],
            }
    return LockPatrol(st).report()


@register_lint_pass("lock-patrol")
def _lock_patrol_pass(jaxpr, meta):
    """Surface runtime patrol findings through the lint framework.

    Inert unless ``meta["patrol"]`` carries a :class:`LockPatrol` view.
    """
    patrol = meta.get("patrol")
    if patrol is None:
        return []
    return patrol.findings()
