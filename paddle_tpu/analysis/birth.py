"""Tracer-leak detector: birth-site attribution for trace-created Tensors.

The to_static record/replay pipeline (core/trace.py, jit/to_static.py)
discovers a compiled step's inputs by watching which pre-existing
Tensors the step READS. That discovery has a failure shape with terrible
ergonomics: a Tensor constructed *inside* a lax sub-trace (a cond branch
or while cond/body lowered via static/nn.py) that is not registered as
trace-created gets classified as a pre-existing capture — and the value
it carries is a tracer of a sub-trace that is already dead by replay
time. JAX eventually notices, deep inside the jitted call, with an
UnexpectedTracerError that names neither the op that created the value
nor the trace it belonged to.

This module turns that failure into an attributed, structured error:

  * **birth sites** — while tracking is enabled, every Tensor
    constructed under a TraceContext records who made it (the creating
    op or function), where (call-site ``file:line``), in which trace
    and under which sub-trace scope (``while_cond#3``). Capture is a
    single frame walk; when tracking is off (the default) the only cost
    anywhere is one ``is not None`` test in ``Tensor.__init__``.
  * **sub-trace scopes** — static/nn.py's ``_lift`` boundaries (the
    cond/while/switch lowering points) push a labelled scope around
    each branch/cond/body trace and run :func:`check_trace` when the
    scope closes.
  * **escape checks** — a read that would capture a tensor born under
    a sub-trace (the leak-in-the-making) records the escape site; when
    the sub-trace closes with such a capture outstanding — or a later
    read touches a tensor whose birth sub-trace is already closed —
    a :class:`TracerLeakError` is raised naming the birth op, the
    birth trace, and the escape site, instead of JAX's opaque error.

Enable with :func:`birth_tracking` (context manager), :func:`enable` /
:func:`disable`, or the ``PADDLE_TPU_ANALYSIS=1`` environment variable
(read at ``paddle_tpu.analysis`` import).
"""
import contextlib
import os
import sys
import threading
import weakref
from collections import namedtuple

from ..core import trace as trace_mod

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_CORE_DIR = os.path.join(_PKG_DIR, "core")
_SELF = os.path.abspath(__file__)

#: Who created a Tensor, where, and under which (sub-)trace.
BirthSite = namedtuple("BirthSite", ["op", "site", "trace", "subtrace"])


class TracerLeakError(RuntimeError):
    """A value born under a sub-trace escaped into its outer trace.

    ``findings`` is a list of machine-readable dicts, each with keys
    ``tensor``, ``birth_op``, ``birth_site``, ``birth_trace`` and
    ``escape_site``.
    """

    def __init__(self, message, findings=()):
        super().__init__(message)
        self.findings = list(findings)


class _BirthState:
    __slots__ = ("enabled", "births", "captures", "stack", "closed",
                 "counter")

    def __init__(self):
        self.enabled = 0
        self.reset()

    def reset(self):
        self.births = {}    # id(tensor) -> (weakref, BirthSite)
        self.captures = {}  # id(tensor) -> escape call-site
        self.stack = []     # active sub-trace tags, innermost last
        self.closed = set()  # tags of exited sub-traces
        self.counter = 0


_state = threading.local()


def _st():
    st = getattr(_state, "birth", None)
    if st is None:
        st = _state.birth = _BirthState()
    return st


def enabled():
    return _st().enabled > 0


def enable():
    """Turn birth tracking on (reentrant; see :func:`birth_tracking`)."""
    st = _st()
    st.enabled += 1
    if st.enabled == 1:
        st.reset()
    trace_mod._birth_hook = _record_birth
    trace_mod._capture_hook = _on_capture


def disable():
    st = _st()
    if st.enabled > 0:
        st.enabled -= 1
    if st.enabled == 0:
        trace_mod._birth_hook = None
        trace_mod._capture_hook = None


@contextlib.contextmanager
def birth_tracking():
    """``with birth_tracking():`` — attribute tracer leaks in the block."""
    enable()
    try:
        yield
    finally:
        disable()


# ---------------------------------------------------------------- hooks

def _is_internal(filename):
    return (filename.startswith(_CORE_DIR) or filename == _SELF)


def _birth_frame():
    """(op, site) of the Tensor construction: the innermost frame
    outside core/ — for op-dispatcher outputs the registered op name is
    lifted from the Op.__call__ frame passed on the way out."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover - interpreter edge
        return "<unknown>", "<unknown>"
    op = None
    for _ in range(32):
        if f is None:
            break
        code = f.f_code
        if _is_internal(code.co_filename):
            if (os.path.basename(code.co_filename) == "dispatch.py"
                    and code.co_name == "__call__" and op is None):
                name = getattr(f.f_locals.get("self"), "name", None)
                if name:
                    op = str(name)
            f = f.f_back
            continue
        site = f"{code.co_filename}:{f.f_lineno}"
        return op or code.co_name, site
    return op or "<unknown>", "<unknown>"


_OPS_DIR = os.path.join(_PKG_DIR, "ops")


def _caller_site():
    """Innermost frame outside core/, ops/ and this module — the escape
    site of a leaking read (the code that consumed the leaked value,
    not the op wrapper it flowed through)."""
    try:
        f = sys._getframe(2)
    except ValueError:  # pragma: no cover
        return "<unknown>"
    for _ in range(32):
        if f is None:
            break
        fn = f.f_code.co_filename
        if _is_internal(fn) or fn.startswith(_OPS_DIR):
            f = f.f_back
            continue
        return f"{f.f_code.co_filename}:{f.f_lineno} ({f.f_code.co_name})"
    return "<unknown>"


def _record_birth(tensor):
    """trace_mod._birth_hook: stamp a birth record on every Tensor
    constructed under an active TraceContext while tracking is on."""
    st = _st()
    if not st.enabled:
        return
    ctx = trace_mod.current_trace()
    if ctx is None:
        return
    op, site = _birth_frame()
    tid = id(tensor)
    births = st.births

    def _gone(_ref, tid=tid, births=births):
        births.pop(tid, None)

    births[tid] = (weakref.ref(tensor, _gone),
                   BirthSite(op, site,
                             f"{ctx.mode}@{id(ctx) & 0xffffff:06x}",
                             st.stack[-1] if st.stack else ""))


def _is_tracer(value):
    import jax.core as jcore
    return isinstance(value, jcore.Tracer)


def _on_capture(ctx, tensor):
    """trace_mod._capture_hook: a read is about to CAPTURE ``tensor``
    as a pre-existing input (record-mode read / jit-mode constant
    embed). If the tensor was born under a sub-trace that has already
    closed and still holds a tracer, that is a live leak — raise with
    full provenance. Otherwise remember the escape site so the
    sub-trace exit check can attribute it."""
    st = _st()
    if not st.enabled:
        return
    rec = st.births.get(id(tensor))
    if rec is None:
        return
    birth = rec[1]
    if not birth.subtrace:
        return
    site = _caller_site()
    st.captures[id(tensor)] = site
    if birth.subtrace not in st.stack and _is_tracer(tensor._value):
        finding = _finding(tensor, birth, site)
        raise TracerLeakError(_message(finding), [finding])


def _finding(tensor, birth, escape_site):
    return {
        "tensor": tensor.name,
        "birth_op": birth.op,
        "birth_site": birth.site,
        "birth_trace": birth.subtrace or birth.trace,
        "escape_site": escape_site or "<captured by outer trace>",
    }


def _message(finding):
    return (
        f"tracer leak: value {finding['tensor']!r} born in "
        f"{finding['birth_op']} at {finding['birth_site']} under trace "
        f"{finding['birth_trace']} escaped its owning trace — captured "
        f"by the outer replay at {finding['escape_site']}. A Tensor "
        "created inside a cond/while sub-trace must be registered with "
        "the active TraceContext (trace_mod.adopt / "
        "ctx.register_created); an unregistered one is mis-classified "
        "as a pre-existing capture and carries a dead sub-trace tracer "
        "into the compiled replay.")


# ------------------------------------------------------------- checking

def birth_of(tensor):
    """The BirthSite recorded for ``tensor``, or None."""
    rec = _st().births.get(id(tensor))
    return rec[1] if rec is not None else None


def check_trace(ctx=None, raise_error=True):
    """Walk ``ctx``'s recorded graph for escaped sub-trace values.

    A leak is a tensor sitting in ``ctx.reads`` (a captured input of
    the would-be compiled program) whose birth record says it was born
    under a sub-trace that is no longer active, and whose value is
    still a tracer of that dead trace. Returns the machine-readable
    findings; raises :class:`TracerLeakError` carrying them when
    ``raise_error`` (the default) and any were found. Run
    automatically at every static/nn.py sub-trace exit and at
    to_static record-phase end while tracking is enabled.
    """
    st = _st()
    if ctx is None:
        ctx = trace_mod.current_trace()
    if ctx is None or not st.births:
        return []
    findings = []
    for tid, tensor in list(ctx.reads.items()):
        rec = st.births.get(tid)
        if rec is None:
            continue
        birth = rec[1]
        if not birth.subtrace or birth.subtrace in st.stack:
            continue
        if not _is_tracer(tensor._value):
            continue
        findings.append(_finding(tensor, birth, st.captures.get(tid)))
    if findings and raise_error:
        raise TracerLeakError(
            "\n".join(_message(f) for f in findings), findings)
    return findings


# ------------------------------------------------------ sub-trace scope

class _NullScope:
    def __enter__(self):
        return ""

    def __exit__(self, *exc):
        return False


_NULL_SCOPE = _NullScope()


class _SubtraceScope:
    __slots__ = ("tag",)

    def __init__(self, label, st):
        st.counter += 1
        self.tag = f"{label}#{st.counter}"

    def __enter__(self):
        _st().stack.append(self.tag)
        return self.tag

    def __exit__(self, exc_type, *exc):
        st = _st()
        if self.tag in st.stack:
            st.stack.remove(self.tag)
        st.closed.add(self.tag)
        if exc_type is None:
            check_trace(trace_mod.current_trace())
        return False


def subtrace(label):
    """Scope a lax sub-trace (cond branch / while cond / while body) for
    leak attribution. No-op unless tracking is enabled; on exit the
    current TraceContext is checked for values that escaped this
    scope."""
    st = _st()
    if not st.enabled:
        return _NULL_SCOPE
    return _SubtraceScope(label, st)
