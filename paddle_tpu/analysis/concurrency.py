"""Static concurrency analyses: thread-role shared-state audit + snapshot lint.

Two AST-based passes over the serving/router/observability sources, both
registered in the PR-5 lint framework and severity-sorted by ``lint_jaxpr``:

``cross-role-write``
    Classifies each method by the thread role it runs on (step-loop /
    http-handler / poller / scrape / router-dispatch / supervisor / caller)
    from a hand-maintained role map of entry points plus within-class
    call-graph propagation.  An attribute *write* on an object reachable
    from two or more roles, without a surrounding ``with <lock>``, is a
    finding.  Known-safe surfaces are encoded in an allowlist whose every
    rule carries source-asserted evidence, so a stale rule rots loudly
    ("allowlist-rot" error finding) instead of silently.

``snapshot-discipline``
    The PR-6 bug class, generalized: a live mutable numpy buffer that is
    also mutated in place elsewhere in the class, handed to a jax dispatch
    or wire serialization without ``.copy()`` laundering.

``audit_default()`` runs both passes over the default source set and is
what the ``tools/lint_graft.py concurrency`` target (tier-1) invokes.
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import os
import re

from .lint import Finding, register_lint_pass, lint_jaxpr

_PKG_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Findings
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AuditFinding(Finding):
    """A cross-role unlocked write (or allowlist bookkeeping record)."""

    key: str = ""
    attr: str = ""
    roles: tuple = ()

    def to_dict(self):
        d = super().to_dict()
        d["key"] = self.key
        d["attr"] = self.attr
        d["roles"] = list(self.roles)
        return d


@dataclasses.dataclass
class SnapshotFinding(Finding):
    """A live mutable buffer handed to a dispatch/serialization sink."""

    attr: str = ""
    mutated_at: tuple = ()

    def to_dict(self):
        d = super().to_dict()
        d["attr"] = self.attr
        d["mutated_at"] = list(self.mutated_at)
        return d


# ---------------------------------------------------------------------------
# Allowlist with source-asserted evidence
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AllowRule:
    """Suppress findings whose key matches ``pattern`` (fnmatch).

    ``evidence`` is a tuple of ``(relpath, regex)`` pairs that must each
    match the named source file's current text; if any fails, the rule is
    dead and an ``allowlist-rot`` *error* finding is emitted instead of a
    suppression — the allowlist rots loudly.
    """

    pattern: str
    justification: str
    evidence: tuple = ()


def _check_evidence(rule, root):
    """Return None if all evidence holds, else a rot description string."""
    for relpath, regex in rule.evidence:
        path = os.path.join(root, relpath)
        try:
            with open(path, "r") as fh:
                text = fh.read()
        except OSError:
            return "evidence file missing: %s" % relpath
        if re.search(regex, text) is None:
            return "evidence regex no longer matches %s: %r" % (relpath, regex)
    return None


# ---------------------------------------------------------------------------
# Role map
# ---------------------------------------------------------------------------

# "basename.py::Class.method" (fnmatch wildcards allowed) -> role or roles.
# This is the hand-maintained seed; within-class call-graph propagation
# spreads roles from these entry points to everything they call.
DEFAULT_ROLE_MAP = {
    # --- serving/engine.py ----------------------------------------------
    # ServingEngine is single-threaded *by contract*: EngineGateway._lock
    # serializes every handler-side entry with the step loop (see the
    # engine allowlist rule's evidence).  The roles below describe where
    # calls originate, not unguarded concurrency.
    "engine.py::ServingEngine.step": "step-loop",
    "engine.py::ServingEngine.add_request": ("caller", "http-handler"),
    "engine.py::ServingEngine.export_kv": ("caller", "http-handler"),
    "engine.py::ServingEngine.import_kv": ("caller", "http-handler"),
    "engine.py::ServingEngine.start_draining": ("caller", "http-handler"),
    "engine.py::ServingEngine.drain": "caller",
    "engine.py::ServingEngine.close": "caller",
    "engine.py::ServingEngine.run": "caller",
    "engine.py::ServingEngine.debug_state": "scrape",
    "engine.py::ServingEngine.request_trace": "scrape",
    # --- serving/router/transport.py ------------------------------------
    "transport.py::EngineGateway._drive": "step-loop",
    "transport.py::EngineGateway.submit": ("caller", "http-handler"),
    "transport.py::EngineGateway.wait": ("caller", "http-handler"),
    "transport.py::EngineGateway.cancel": ("caller", "http-handler"),
    "transport.py::EngineGateway.prefill": ("caller", "http-handler"),
    "transport.py::EngineGateway.import_request": ("caller", "http-handler"),
    "transport.py::EngineGateway.handle_*": "http-handler",
    "transport.py::EngineGateway.drain": "caller",
    "transport.py::EngineGateway.kill": "caller",
    "transport.py::EngineGateway.close": "caller",
    # --- serving/router/core.py -----------------------------------------
    "core.py::Router.submit": "caller",
    "core.py::Router.generate": "caller",
    "core.py::Router._drive": "router-dispatch",
    "core.py::Router._drive_disagg": "router-dispatch",
    "core.py::Router.refresh": ("caller", "router-dispatch"),
    "core.py::Router.state": "scrape",
    "core.py::RouterTicket._finish": "router-dispatch",
    "core.py::RouterTicket.done": "caller",
    "core.py::RouterTicket.result": "caller",
    "core.py::RouterTicket.cancel": "caller",
    # --- observability/fleet/poller.py ----------------------------------
    "poller.py::FleetPoller._loop": "poller",
    "poller.py::FleetPoller.poll_once": ("poller", "caller"),
    "poller.py::FleetPoller._scrape": "scrape-worker",
    "poller.py::FleetPoller.snapshot": ("scrape", "caller"),
    "poller.py::FleetPoller.fleet_health": ("scrape", "caller"),
    "poller.py::FleetPoller.fleet_tenants": ("scrape", "caller"),
    "poller.py::FleetPoller.prometheus_text": ("scrape", "caller"),
    "poller.py::FleetPoller.detector_counts": ("scrape", "caller"),
    "poller.py::FleetPoller.start": "caller",
    "poller.py::FleetPoller.stop": "caller",
    # --- observability/registry.py --------------------------------------
    # Every registry child is written from instrumented code paths (the
    # step loop) and read by scrapes; MetricsRegistry._lock guards both.
    "registry.py::MetricsRegistry.*": ("step-loop", "scrape"),
    "registry.py::_CounterChild.*": ("step-loop", "scrape"),
    "registry.py::_GaugeChild.*": ("step-loop", "scrape"),
    "registry.py::_HistogramChild.*": ("step-loop", "scrape"),
}

_WRITE_MUTATORS = {
    "append",
    "appendleft",
    "add",
    "remove",
    "discard",
    "clear",
    "update",
    "extend",
    "insert",
    "pop",
    "popleft",
    "setdefault",
    "put",
}

_LOCKISH = re.compile(r"lock|cond|mutex|guard", re.IGNORECASE)

# Constructors whose instances synchronize internally: mutator calls on an
# attribute bound to one of these in __init__ are not unlocked writes.
# Event/Queue/Semaphore are interpreter-level atomic; Reservoir and
# StepLedger are repo classes that take their own lock in every mutator
# (their docstrings say "thread-safe" and the evidence is one grep away).
_SYNC_CTORS = {
    "Event",
    "Queue",
    "SimpleQueue",
    "LifoQueue",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "Reservoir",
    "StepLedger",
}


def _self_root(node):
    """Attribute root for a ``self.X[...]...`` chain, or None."""
    n = node
    while isinstance(n, (ast.Subscript, ast.Attribute)):
        if (
            isinstance(n, ast.Attribute)
            and isinstance(n.value, ast.Name)
            and n.value.id == "self"
        ):
            return n.attr
        n = n.value
    return None


class _MethodScan(ast.NodeVisitor):
    """Collect per-method: self-calls, self-attr occurrences, lock context."""

    def __init__(self):
        self.calls = set()  # names of self.method() calls
        self.unlocked_calls = set()  # self-calls made outside lock context
        # (attr, "read"|"write", locked: bool, lineno, via: "bind"|"mutate")
        self.occurrences = []
        self._lock_depth = 0

    # -- lock context -----------------------------------------------------

    def visit_With(self, node):
        lockish = 0
        for item in node.items:
            try:
                txt = ast.unparse(item.context_expr)
            except Exception:
                txt = ""
            if _LOCKISH.search(txt):
                lockish += 1
        self._lock_depth += lockish
        for stmt in node.body:
            self.visit(stmt)
        self._lock_depth -= lockish

    visit_AsyncWith = visit_With

    # -- occurrences ------------------------------------------------------

    def _note(self, attr, kind, lineno, via="bind"):
        if attr is not None:
            self.occurrences.append(
                (attr, kind, self._lock_depth > 0, lineno, via)
            )

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name) and node.value.id == "self":
            kind = "write" if isinstance(node.ctx, (ast.Store, ast.Del)) else "read"
            self._note(node.attr, kind, node.lineno)
        self.generic_visit(node)

    def visit_Subscript(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            root = _self_root(node)
            if root is not None:
                self._note(root, "write", node.lineno, via="mutate")
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if isinstance(fn.value, ast.Name) and fn.value.id == "self":
                self.calls.add(fn.attr)
                if self._lock_depth == 0:
                    self.unlocked_calls.add(fn.attr)
            elif fn.attr in _WRITE_MUTATORS:
                root = _self_root(fn.value)
                if root is not None:
                    self._note(root, "write", node.lineno, via="mutate")
        self.generic_visit(node)


def _method_name(node):
    return node.name


def _sync_attrs_from_init(fn_node):
    """Attrs bound to internally-synchronized objects in ``__init__``."""
    out = set()
    for node in ast.walk(fn_node):
        if not isinstance(node, ast.Assign) or not isinstance(node.value, ast.Call):
            continue
        fn = node.value.func
        name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", None)
        if name not in _SYNC_CTORS:
            continue
        for tgt in node.targets:
            if (
                isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"
            ):
                out.add(tgt.attr)
    return out


def _scan_class(cls_node):
    """Return ({method: _MethodScan}, sync_attrs) for a class body.

    ``__init__``/``__new__`` writes are excluded (construction
    happens-before publication), but ``__init__`` is still mined for
    attributes bound to internally-synchronized objects.
    """
    scans = {}
    sync_attrs = set()
    for item in cls_node.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if item.name in ("__init__", "__new__"):
                sync_attrs |= _sync_attrs_from_init(item)
                continue
            sc = _MethodScan()
            for stmt in item.body:
                sc.visit(stmt)
            scans[item.name] = sc
    return scans, sync_attrs


def _seed_roles(basename, clsname, methods, role_map):
    """Map method -> set of roles from the role map (fnmatch on full key)."""
    roles = {m: set() for m in methods}
    for pattern, role in role_map.items():
        pat_file, _, pat_meth = pattern.partition("::")
        if not fnmatch.fnmatch(basename, pat_file):
            continue
        for m in methods:
            full = "%s.%s" % (clsname, m)
            if fnmatch.fnmatch(full, pat_meth):
                if isinstance(role, str):
                    roles[m].add(role)
                else:
                    roles[m].update(role)
    return roles


def _propagate(roles, scans):
    """Fixpoint: a method called from a role runs on that role too."""
    changed = True
    while changed:
        changed = False
        for m, sc in scans.items():
            for callee in sc.calls:
                if callee in roles and not roles[m] <= roles[callee]:
                    roles[callee] |= roles[m]
                    changed = True
    return roles


def _normalize_sources(sources):
    """Yield (display_name, text) pairs from paths or (name, text) tuples."""
    for src in sources:
        if isinstance(src, tuple):
            yield src
        else:
            path = src if os.path.isabs(src) else os.path.join(_PKG_DIR, src)
            try:
                with open(path, "r") as fh:
                    yield src, fh.read()
            except OSError:
                continue


def _audit_sources(sources, role_map, allow, root):
    findings = []
    rule_hits = {id(r): 0 for r in allow}
    rot = {}
    for rule in allow:
        why = _check_evidence(rule, root)
        if why is not None:
            rot[id(rule)] = why
            findings.append(
                AuditFinding(
                    pass_name="cross-role-write",
                    severity="error",
                    site=rule.pattern,
                    detail="allowlist-rot: %s (rule: %s)" % (why, rule.justification),
                    key=rule.pattern,
                )
            )
    for name, text in sources:
        basename = os.path.basename(name)
        try:
            tree = ast.parse(text)
        except SyntaxError as e:
            findings.append(
                AuditFinding(
                    pass_name="cross-role-write",
                    severity="warning",
                    site="%s:%s" % (basename, e.lineno or 0),
                    detail="unparseable source: %s" % e.msg,
                )
            )
            continue
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            scans, sync_attrs = _scan_class(cls)
            if not scans:
                continue
            roles = _seed_roles(basename, cls.name, scans.keys(), role_map)
            seeded = {m for m, r in roles.items() if r}
            roles = _propagate(roles, scans)
            # Caller-lock propagation: a helper reached ONLY through
            # in-class call sites that all sit inside a lock context runs
            # under the caller's lock.  Seeded entry points never qualify
            # (external callers hold nothing).
            called = set()
            called_unlocked = set()
            for sc in scans.values():
                called |= sc.calls
                called_unlocked |= sc.unlocked_calls
            lock_inherited = {
                m
                for m in scans
                if m in called and m not in called_unlocked and m not in seeded
            }
            # attr -> set of roles that touch it / that write it unlocked
            attr_roles = {}
            attr_unlocked_writes = {}  # attr -> [(method, lineno, roles)]
            for m, sc in scans.items():
                mroles = roles.get(m, set())
                if not mroles:
                    continue
                for attr, kind, locked, lineno, via in sc.occurrences:
                    if attr.startswith("__"):
                        continue
                    attr_roles.setdefault(attr, set()).update(mroles)
                    if kind != "write" or locked or m in lock_inherited:
                        continue
                    if via == "mutate" and attr in sync_attrs:
                        # Internally-synchronized container (Event, Queue,
                        # Reservoir, StepLedger, ...): its mutators are safe.
                        continue
                    attr_unlocked_writes.setdefault(attr, []).append(
                        (m, lineno, mroles)
                    )
            for attr, rset in sorted(attr_roles.items()):
                if len(rset) < 2 or attr not in attr_unlocked_writes:
                    continue
                if _LOCKISH.search(attr):
                    # The lock object itself (self._lock = ...) is not data.
                    continue
                for m, lineno, mroles in attr_unlocked_writes[attr]:
                    key = "%s::%s.%s.%s" % (basename, cls.name, m, attr)
                    matched = None
                    for rule in allow:
                        if id(rule) in rot:
                            continue
                        if fnmatch.fnmatch(key, rule.pattern):
                            matched = rule
                            break
                    if matched is not None:
                        rule_hits[id(matched)] += 1
                        continue
                    findings.append(
                        AuditFinding(
                            pass_name="cross-role-write",
                            severity="error",
                            site="%s:%d" % (basename, lineno),
                            detail=(
                                "unlocked write to %s.%s in %s.%s; attribute "
                                "reachable from roles {%s}"
                                % (
                                    cls.name,
                                    attr,
                                    cls.name,
                                    m,
                                    ", ".join(sorted(attr_roles[attr])),
                                )
                            ),
                            key=key,
                            attr=attr,
                            roles=tuple(sorted(attr_roles[attr])),
                        )
                    )
    for rule in allow:
        if id(rule) in rot:
            continue
        n = rule_hits[id(rule)]
        if n:
            findings.append(
                AuditFinding(
                    pass_name="cross-role-write",
                    severity="info",
                    site=rule.pattern,
                    detail="allowlisted %d write(s): %s" % (n, rule.justification),
                    key=rule.pattern,
                )
            )
        else:
            findings.append(
                AuditFinding(
                    pass_name="cross-role-write",
                    severity="warning",
                    site=rule.pattern,
                    detail=(
                        "unused allowlist rule (matched nothing): %s"
                        % rule.justification
                    ),
                    key=rule.pattern,
                )
            )
    return findings


# ---------------------------------------------------------------------------
# Snapshot-discipline pass (the PR-6 bug class, generalized)
# ---------------------------------------------------------------------------

# Call names that launder a buffer into an independent snapshot.
_SNAPSHOT_LAUNDER = {
    "copy",
    "deepcopy",
    "array",
    "ascontiguousarray",
    "tobytes",
    "tolist",
    "astype",
    "item",
}

# Callee attribute names that hand a buffer to a dispatch or the wire.
_SNAPSHOT_SINKS = {"_timed_call", "device_put", "asarray", "pack", "dumps"}

# In-place mutation spellings on an array attribute.
_INPLACE_MUTATORS = {"fill", "sort", "put", "partition", "resize"}


def _is_laundered(node):
    """True if the expr's value is a fresh snapshot (``.copy()`` etc.)."""
    if isinstance(node, ast.Call):
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _SNAPSHOT_LAUNDER:
            return True
        if isinstance(fn, ast.Name) and fn.id in _SNAPSHOT_LAUNDER:
            return True
    return False


def _live_refs(node):
    """self-attrs referenced live (unlaundered) inside an expression."""
    if node is None:
        return
    if _is_laundered(node):
        return
    root = _self_root(node) if isinstance(node, (ast.Attribute, ast.Subscript)) else None
    if root is not None:
        yield root, node.lineno
        return
    for child in ast.iter_child_nodes(node):
        yield from _live_refs(child)


class _SnapshotScan(ast.NodeVisitor):
    """Per-class: in-place mutated attrs + live attr refs at sink calls."""

    def __init__(self):
        self.mutated = {}  # attr -> [lineno]
        self.sunk = []  # (attr, sink_name, lineno)

    def visit_Subscript(self, node):
        if isinstance(node.ctx, (ast.Store, ast.Del)):
            root = _self_root(node)
            if root is not None:
                self.mutated.setdefault(root, []).append(node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node):
        fn = node.func
        if isinstance(fn, ast.Attribute):
            if fn.attr in _INPLACE_MUTATORS:
                root = _self_root(fn.value)
                if root is not None:
                    self.mutated.setdefault(root, []).append(node.lineno)
            if fn.attr in _SNAPSHOT_SINKS:
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    for attr, lineno in _live_refs(arg):
                        self.sunk.append((attr, fn.attr, lineno))
        elif isinstance(fn, ast.Name) and fn.id in _SNAPSHOT_SINKS:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for attr, lineno in _live_refs(arg):
                    self.sunk.append((attr, fn.attr, lineno))
        self.generic_visit(node)


def _snapshot_sources(sources):
    findings = []
    for name, text in sources:
        basename = os.path.basename(name)
        try:
            tree = ast.parse(text)
        except SyntaxError:
            continue
        for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
            sc = _SnapshotScan()
            for item in cls.body:
                if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for stmt in item.body:
                        sc.visit(stmt)
            seen = set()
            for attr, sink, lineno in sc.sunk:
                if attr not in sc.mutated:
                    continue
                key = (cls.name, attr, sink, lineno)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(
                    SnapshotFinding(
                        pass_name="snapshot-discipline",
                        severity="error",
                        site="%s:%d" % (basename, lineno),
                        detail=(
                            "live buffer %s.%s handed to %s() but mutated in "
                            "place at %s lines %s; snapshot with .copy() "
                            "before the sink (PR-6 bug class)"
                            % (
                                cls.name,
                                attr,
                                sink,
                                basename,
                                ",".join(str(n) for n in sc.mutated[attr][:5]),
                            )
                        ),
                        attr=attr,
                        mutated_at=tuple(sc.mutated[attr][:5]),
                    )
                )
    return findings


# ---------------------------------------------------------------------------
# Registered passes + default audit
# ---------------------------------------------------------------------------

# Default allowlist for the real tree.  Every rule carries evidence regexes
# asserted against the live source: if the guarded pattern disappears, the
# rule turns into an allowlist-rot error instead of silently suppressing.
DEFAULT_AUDIT_ALLOW = (
    AllowRule(
        pattern="engine.py::ServingEngine.*",
        justification=(
            "ServingEngine is single-threaded by contract: every handler-"
            "side entry (submit/wait/cancel/prefill/import_request/drain) "
            "reaches the engine through EngineGateway under its RLock, and "
            "_drive() holds the same lock across step()."
        ),
        evidence=(
            ("serving/router/transport.py", r"self\._lock = threading\.RLock\(\)"),
            (
                "serving/router/transport.py",
                r"def submit\((.|\n){0,1200}?with self\._lock",
            ),
            (
                "serving/router/transport.py",
                r"with self\._lock:\n(.|\n){0,200}?"
                r"worked = bool\(self\.engine\.step\(\)\)",
            ),
        ),
    ),
    AllowRule(
        pattern="transport.py::EngineGateway.kill._dead",
        justification=(
            "kill() flips the monotonic _dead flag without the lock on "
            "purpose: SIGKILL semantics must not wait for a step that is "
            "holding the gateway lock; readers tolerate staleness."
        ),
        evidence=(
            ("serving/router/transport.py", r"self\._dead = True"),
        ),
    ),
    AllowRule(
        pattern="core.py::RouterTicket._finish.*",
        justification=(
            "RouterTicket publishes result fields before _done.set(); "
            "consumers only read them after waiting on the event, so the "
            "Event provides the happens-before edge (event-sequenced "
            "publish)."
        ),
        evidence=(
            ("serving/router/core.py", r"self\._done\.set\(\)"),
        ),
    ),
)

DEFAULT_AUDIT_SOURCES = (
    "serving/engine.py",
    "serving/router/transport.py",
    "serving/router/core.py",
    "serving/router/breaker.py",
    "serving/router/journal.py",
    "observability/fleet/poller.py",
    "observability/registry.py",
)

DEFAULT_SNAPSHOT_SOURCES = (
    "serving/engine.py",
    "serving/kv_pool.py",
    "serving/paged/pool.py",
    "serving/sched/sampling.py",
    "serving/kv_wire.py",
)


@register_lint_pass("cross-role-write")
def _cross_role_write_pass(jaxpr, meta):
    """Thread-role shared-state auditor. Inert without ``meta["thread_audit"]``."""
    cfg = meta.get("thread_audit")
    if cfg is None:
        return []
    sources = list(_normalize_sources(cfg.get("sources", DEFAULT_AUDIT_SOURCES)))
    role_map = cfg.get("role_map", DEFAULT_ROLE_MAP)
    allow = cfg.get("allow", DEFAULT_AUDIT_ALLOW)
    root = cfg.get("root", _PKG_DIR)
    return _audit_sources(sources, role_map, allow, root)


@register_lint_pass("snapshot-discipline")
def _snapshot_discipline_pass(jaxpr, meta):
    """Live-buffer-to-dispatch lint. Inert without ``meta["snapshot_audit"]``."""
    cfg = meta.get("snapshot_audit")
    if cfg is None:
        return []
    sources = list(_normalize_sources(cfg.get("sources", DEFAULT_SNAPSHOT_SOURCES)))
    return _snapshot_sources(sources)


def audit_default():
    """Run both static passes over the default source set (tier-1 entry)."""
    return lint_jaxpr(
        None,
        passes=["cross-role-write", "snapshot-discipline"],
        thread_audit={"sources": DEFAULT_AUDIT_SOURCES},
        snapshot_audit={"sources": DEFAULT_SNAPSHOT_SOURCES},
    )
