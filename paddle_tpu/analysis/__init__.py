"""Trace/graph static analysis: tracer-leak detection + jaxpr lint.

Two tools over the compiler path, mirroring what PR 3/4 gave the
serving path (the attributed compile watchdog):

* **Tracer-leak detector** (:mod:`.birth`) — birth-site attribution
  for Tensors created under a TraceContext, sub-trace scopes at the
  static/nn.py cond/while lowering boundaries, and
  :func:`check_trace`, which turns the classic dy2static failure
  (a constant born inside a ``while_cond`` sub-trace captured by the
  outer replay) into a structured :class:`TracerLeakError` naming the
  birth op, the birth trace and the escape site — instead of JAX's
  opaque UnexpectedTracerError. Off by default; enable with
  :func:`birth_tracking` or ``PADDLE_TPU_ANALYSIS=1``.

* **Jaxpr lint** (:mod:`.lint`) — :func:`lint_jaxpr` runs pluggable
  passes (``f64-upcast``, ``donation``, ``dynamic-shape-risk``,
  ``host-callback``) over lowered programs and emits machine-readable
  findings. Entry points: ``ServingEngine.lint()`` (decode
  executable + donation/watchdog cross-checks),
  ``TracedFunction.lint()`` (to_static compiled steps), and
  ``tools/lint_graft.py`` (repo self-lint, JSON output, nonzero exit
  on error findings).

Quick start::

    from paddle_tpu import analysis

    with analysis.birth_tracking():      # attribute any tracer leak
        traced_step(x)                   # raises TracerLeakError w/ provenance

    findings = analysis.lint_fn(fn, jnp.ones((8, 8)))
    print(analysis.findings_to_json(findings))

    engine.lint()                        # serving decode executable
"""
import os as _os

from .birth import (  # noqa: F401
    BirthSite, TracerLeakError, birth_of, birth_tracking, check_trace,
    disable, enable, enabled, subtrace,
)
from .lint import (  # noqa: F401
    Finding, SEVERITIES, donated_invars_from_argnums, eqn_site,
    findings_to_json, iter_eqns, lint_fn, lint_jaxpr, lint_passes,
    register_lint_pass,
)

if _os.environ.get("PADDLE_TPU_ANALYSIS", "").lower() not in (
        "", "0", "false", "off"):
    enable()
