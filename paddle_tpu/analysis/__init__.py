"""Trace/graph static analysis: tracer-leak detection, jaxpr lint, and
concurrency analysis.

Four tools, mirroring what PR 3/4 gave the serving path (the attributed
compile watchdog):

* **Tracer-leak detector** (:mod:`.birth`) — birth-site attribution
  for Tensors created under a TraceContext, sub-trace scopes at the
  static/nn.py cond/while lowering boundaries, and
  :func:`check_trace`, which turns the classic dy2static failure
  (a constant born inside a ``while_cond`` sub-trace captured by the
  outer replay) into a structured :class:`TracerLeakError` naming the
  birth op, the birth trace and the escape site — instead of JAX's
  opaque UnexpectedTracerError. Off by default; enable with
  :func:`birth_tracking` or ``PADDLE_TPU_ANALYSIS=1``.

* **Jaxpr lint** (:mod:`.lint`) — :func:`lint_jaxpr` runs pluggable
  passes (``f64-upcast``, ``donation``, ``dynamic-shape-risk``,
  ``host-callback``) over lowered programs and emits machine-readable
  findings. Entry points: ``ServingEngine.lint()`` (decode
  executable + donation/watchdog cross-checks),
  ``TracedFunction.lint()`` (to_static compiled steps), and
  ``tools/lint_graft.py`` (repo self-lint, JSON output, nonzero exit
  on error findings).

* **Lock patrol** (:mod:`.threads`) — lockdep-style runtime deadlock
  lint: :func:`lock_patrol` wraps every Lock/RLock/Condition created
  inside ``paddle_tpu.*`` with a site-attributed proxy, records the
  acquired-while-holding graph across threads, and reports cycles
  (``lock-order``) and locks held across timed AOT dispatches or
  blocking socket calls (``lock-held-across-dispatch``). Off by
  default — same gating as :func:`birth_tracking`; when off the only
  hot-path residue is one boolean test.

* **Concurrency lint** (:mod:`.concurrency`) — static AST passes:
  ``cross-role-write`` classifies methods by thread role (step-loop /
  http-handler / poller / scrape / router-dispatch / caller) and flags
  unlocked attribute writes reachable from two or more roles, against
  an allowlist whose rules carry source-asserted evidence so they rot
  loudly; ``snapshot-discipline`` flags live mutable buffers (mutated
  in place elsewhere in the class) handed to a jax dispatch or wire
  serialization — the PR-6 ``.copy()``-before-upload bug class.
  :func:`audit_default` runs both over the serving stack and is the
  ``tools/lint_graft.py concurrency`` tier-1 target.

Quick start::

    from paddle_tpu import analysis

    with analysis.birth_tracking():      # attribute any tracer leak
        traced_step(x)                   # raises TracerLeakError w/ provenance

    findings = analysis.lint_fn(fn, jnp.ones((8, 8)))
    print(analysis.findings_to_json(findings))

    engine.lint()                        # serving decode executable

    with analysis.lock_patrol() as patrol:   # race/deadlock drill
        drive_engine()
    assert not patrol.findings()

    findings = analysis.audit_default()  # static concurrency audit
"""
import os as _os

from .birth import (  # noqa: F401
    BirthSite, TracerLeakError, birth_of, birth_tracking, check_trace,
    disable, enable, enabled, subtrace,
)
from .lint import (  # noqa: F401
    Finding, SEVERITIES, donated_invars_from_argnums, eqn_site,
    findings_to_json, iter_eqns, lint_fn, lint_jaxpr, lint_passes,
    register_lint_pass,
)
from .threads import (  # noqa: F401
    HeldAcrossFinding, LockOrderFinding, LockPatrol, disable_patrol,
    enable_patrol, lock_patrol, note_blocking, patrol_report,
)
from .concurrency import (  # noqa: F401
    AllowRule, AuditFinding, SnapshotFinding, audit_default,
)

if _os.environ.get("PADDLE_TPU_ANALYSIS", "").lower() not in (
        "", "0", "false", "off"):
    enable()
    enable_patrol()
