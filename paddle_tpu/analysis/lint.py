"""Jaxpr lint framework: pluggable static-analysis passes over lowered
programs.

The Paddle reference inspects programs at the ProgramDesc/IR level
(graph passes over op descs); our compiled unit is a jaxpr, so this is
the analogue: ``lint_jaxpr(target)`` walks a lowered program (and every
sub-jaxpr: cond branches, while cond/body, scan bodies, inner pjit
calls) through registered passes, each emitting machine-readable
findings ``{"pass", "severity", "site", "detail"}``.

Built-in passes:

``f64-upcast``
    any equation producing float64 from non-float64 inputs (or from
    nothing: a fresh f64 constant/iota) — silent 2x memory + compute
    on the hot path. Severity ``error``.
``donation``
    large array inputs compiled WITHOUT buffer donation on a backend
    that aliases donated buffers — the double-buffering the serving
    engine's kc/vc/pos donation exists to avoid. Needs
    ``donated_invars`` (see :func:`donated_invars_from_argnums`) and
    ``backend_aliases`` metadata; emits nothing on non-aliasing
    backends (CPU), which is exactly what
    ``ServingMetrics.snapshot()["kv_donation"]`` reports there.
    Severity ``warning``.
``dynamic-shape-risk``
    one executable key compiled under more than one distinct
    abstract-shape signature, read from a PR-3 CompileWatchdog
    (``watchdog=`` metadata; ``CompileWatchdog.signature_groups()``)
    — the recompile shape of python-int shapes derived from traced
    values, attributed to the recorded dispatch call-sites. Severity
    ``warning``.
``host-callback``
    ``pure_callback`` / ``io_callback`` / ``debug_callback`` equations
    inside the program — a host round-trip per dispatch inside a
    decode/train step. Severity ``warning``.

Passes are functions ``(jaxpr_or_None, meta) -> list[Finding]``
registered via :func:`register_lint_pass`; unknown metadata keys are
ignored by passes that don't use them, so one ``lint_jaxpr`` call can
feed every pass.
"""
import dataclasses
import json

import numpy as np

SEVERITIES = ("error", "warning", "info")
_SEV_ORDER = {s: i for i, s in enumerate(SEVERITIES)}


@dataclasses.dataclass
class Finding:
    """One lint finding. ``to_dict()`` is the machine-readable schema
    (the ``pass`` key carries the pass name)."""
    pass_name: str
    severity: str
    site: str
    detail: str

    def to_dict(self):
        return {"pass": self.pass_name, "severity": self.severity,
                "site": self.site, "detail": self.detail}

    def __str__(self):
        return (f"[{self.severity}] {self.pass_name} @ {self.site}: "
                f"{self.detail}")


def findings_to_json(findings, indent=2):
    return json.dumps([f.to_dict() for f in findings], indent=indent)


_PASSES = {}


def register_lint_pass(name):
    """Register ``fn(jaxpr_or_None, meta) -> list[Finding]`` under
    ``name``. Re-registering replaces (tests stub passes this way)."""
    def deco(fn):
        _PASSES[name] = fn
        return fn
    return deco


def lint_passes():
    """Names of all registered passes, sorted."""
    return sorted(_PASSES)


# ------------------------------------------------------------ jaxpr walk

def _as_jaxprs(v):
    import jax
    if isinstance(v, jax.core.ClosedJaxpr):
        return [v.jaxpr]
    if isinstance(v, jax.core.Jaxpr):
        return [v]
    if isinstance(v, (list, tuple)):
        return [j for x in v for j in _as_jaxprs(x)]
    return []


def iter_eqns(jaxpr):
    """Every equation in ``jaxpr`` including all nested sub-jaxprs
    (cond branches, while cond/body, scan/pjit bodies)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for v in eqn.params.values():
            for sub in _as_jaxprs(v):
                yield from iter_eqns(sub)


def eqn_site(eqn):
    """``file:line (function)`` of the user frame that emitted the
    equation, via jax's source_info; "<unknown>" when unavailable."""
    try:
        from jax._src import source_info_util
        frame = source_info_util.user_frame(eqn.source_info)
        if frame is not None:
            return (f"{frame.file_name}:{frame.start_line} "
                    f"({frame.function_name})")
    except Exception:
        pass
    return "<unknown>"


def _resolve(target):
    """target -> core Jaxpr. Accepts ClosedJaxpr (jax.make_jaxpr
    output), a raw Jaxpr, anything exposing ``.jaxpr`` (jax.stages
    Traced), a ServingEngine (delegates to ``engine.lint``'s
    resolution), or None (meta-only passes still run)."""
    import jax
    if target is None:
        return None
    if isinstance(target, jax.core.Jaxpr):
        return target
    if isinstance(target, jax.core.ClosedJaxpr):
        return target.jaxpr
    inner = getattr(target, "jaxpr", None)
    if inner is not None:
        return _resolve(inner)
    raise TypeError(
        f"lint_jaxpr target {type(target).__name__} is not a jaxpr; "
        "pass a jax.make_jaxpr(...) result, an object with .jaxpr, or "
        "use ServingEngine.lint() / TracedFunction.lint() for compiled "
        "entry points")


def lint_jaxpr(target=None, passes=None, **meta):
    """Run lint passes over a lowered program; returns findings sorted
    most-severe first.

    ``target`` — ClosedJaxpr / Jaxpr / object with ``.jaxpr``; or None
    to run only metadata-driven passes (e.g. ``dynamic-shape-risk``
    over a ``watchdog=``). ``passes`` selects a subset by name.
    Metadata used by the built-ins: ``donated_invars``,
    ``backend_aliases``, ``min_donation_bytes``, ``watchdog``.
    """
    jaxpr = _resolve(target)
    names = list(passes) if passes is not None else lint_passes()
    findings = []
    for name in names:
        fn = _PASSES.get(name)
        if fn is None:
            raise KeyError(f"unknown lint pass {name!r}; registered: "
                           f"{lint_passes()}")
        findings.extend(fn(jaxpr, meta) or [])
    findings.sort(key=lambda f: _SEV_ORDER.get(f.severity, len(SEVERITIES)))
    return findings


def lint_fn(fn, *args, passes=None, **meta):
    """Convenience: ``lint_jaxpr(jax.make_jaxpr(fn)(*args), ...)``.
    ``args`` may be arrays or jax.ShapeDtypeStruct avals."""
    import jax
    return lint_jaxpr(jax.make_jaxpr(fn)(*args), passes=passes, **meta)


def donated_invars_from_argnums(args, donate_argnums):
    """Flattened per-invar donation flags for positional ``args``
    compiled with ``donate_argnums`` — the shape the ``donation`` pass
    consumes (jaxpr invars are the flattened leaves of the positional
    args, in order)."""
    import jax
    donate = set(donate_argnums)
    flags = []
    for i, a in enumerate(args):
        n = len(jax.tree_util.tree_leaves(a))
        flags.extend([i in donate] * n)
    return tuple(flags)


# ---------------------------------------------------------------- passes

_F64 = np.dtype("float64")


def _aval_dtype(atom):
    aval = getattr(atom, "aval", None)
    return getattr(aval, "dtype", None)


@register_lint_pass("f64-upcast")
def _pass_f64_upcast(jaxpr, meta):
    if jaxpr is None:
        return []
    findings = []
    for eqn in iter_eqns(jaxpr):
        out64 = [v for v in eqn.outvars if _aval_dtype(v) == _F64]
        if not out64:
            continue
        in_dtypes = [dt for dt in (_aval_dtype(v) for v in eqn.invars)
                     if dt is not None]
        if in_dtypes and all(dt == _F64 for dt in in_dtypes):
            continue  # f64 flowing through; the original upcast is flagged
        src = ",".join(sorted({str(dt) for dt in in_dtypes})) or "<none>"
        findings.append(Finding(
            "f64-upcast", "error", eqn_site(eqn),
            f"{eqn.primitive.name} produces float64 from [{src}] — "
            "silent f64 promotion on the hot path (2x memory/compute; "
            "TPUs emulate f64)"))
    return findings


@register_lint_pass("donation")
def _pass_donation(jaxpr, meta):
    if jaxpr is None:
        return []
    aliases = meta.get("backend_aliases")
    if aliases is None:
        import jax
        aliases = jax.devices()[0].platform != "cpu"
    if not aliases:
        # non-aliasing backend (CPU): donation is pure dispatch
        # overhead there — matches snapshot()["kv_donation"]
        # {"effective": False}
        return []
    donated = tuple(meta.get("donated_invars") or ())
    min_bytes = int(meta.get("min_donation_bytes", 1 << 20))
    findings = []
    for i, var in enumerate(jaxpr.invars):
        aval = getattr(var, "aval", None)
        if aval is None or not hasattr(aval, "shape"):
            continue
        nbytes = int(np.prod(aval.shape or (1,))) * np.dtype(aval.dtype).itemsize
        is_donated = donated[i] if i < len(donated) else False
        if nbytes >= min_bytes and not is_donated:
            findings.append(Finding(
                "donation", "warning", f"invar[{i}]",
                f"{aval.dtype}[{','.join(str(d) for d in aval.shape)}] "
                f"({nbytes} bytes) compiled without donation on an "
                "aliasing backend — the update double-buffers instead "
                "of aliasing in place (serving donates kc/vc/pos; see "
                "ServingConfig(donate_buffers=))"))
    return findings


@register_lint_pass("dynamic-shape-risk")
def _pass_dynamic_shape_risk(jaxpr, meta):
    watchdog = meta.get("watchdog")
    if watchdog is None:
        return []
    findings = []
    for key, group in sorted(watchdog.signature_groups().items()):
        sigs = group["signatures"]
        if len(sigs) <= 1:
            continue
        sites = group["call_sites"]
        findings.append(Finding(
            "dynamic-shape-risk", "warning", sites[-1],
            f"executable {key} compiled under {len(sigs)} distinct "
            "abstract-shape signatures — a python-int shape derived "
            "from traced values re-specializes per value (recompile "
            f"source); signatures: {sigs[:4]}"))
    return findings


_CALLBACK_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "host_callback",
    "outside_call", "python_callback",
})


@register_lint_pass("host-callback")
def _pass_host_callback(jaxpr, meta):
    if jaxpr is None:
        return []
    findings = []
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name in _CALLBACK_PRIMITIVES:
            findings.append(Finding(
                "host-callback", "warning", eqn_site(eqn),
                f"{eqn.primitive.name} inside the compiled program — "
                "one host round-trip per dispatch (debug print / "
                "pure_callback left in a decode/train step?)"))
    return findings
