"""Profiler.

Reference parity: paddle/fluid/platform/profiler.h:127 RecordEvent /
:213 EnableProfiler + python/paddle/fluid/profiler.py:314. TPU-native:
jax.profiler (XPlane) captures real device timelines viewable in
TensorBoard / Perfetto; RecordEvent lowers to jax.profiler.TraceAnnotation
+ jax.named_scope so op metadata reaches the XLA trace, the analogue of
the reference's NVTX/CUPTI annotations.
"""
import contextlib
import time

import jax


class RecordEvent:
    """RAII scope annotation (reference: profiler.h:127)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._cm = None

    def __enter__(self):
        self._cm = contextlib.ExitStack()
        self._cm.enter_context(jax.profiler.TraceAnnotation(self.name))
        self._cm.enter_context(jax.named_scope(self.name))
        return self

    def __exit__(self, *exc):
        self._cm.close()
        return False

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__()


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler traces."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir="./profiler_log", timer_only=False):
        self.log_dir = log_dir
        self.timer_only = timer_only
        self._started = False
        self._step_times = []
        self._t0 = None

    def start(self):
        if not self.timer_only:
            jax.profiler.start_trace(self.log_dir)
        self._started = True
        self._t0 = time.perf_counter()

    def stop(self):
        if self._started and not self.timer_only:
            jax.profiler.stop_trace()
        self._started = False

    def step(self):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now

    def step_info(self, unit=None):
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        arr = np.asarray(self._step_times[1:] or self._step_times)
        return (f"avg step {arr.mean() * 1000:.3f} ms, "
                f"min {arr.min() * 1000:.3f} ms, max {arr.max() * 1000:.3f} ms")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def summary(self, **kwargs):
        return self.step_info()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """Legacy fluid.profiler.profiler context (reference:
    python/paddle/fluid/profiler.py:314)."""
    p = Profiler(log_dir=profile_path or "./profiler_log")
    p.start()
    try:
        yield p
    finally:
        p.stop()


def start_profiler(state="All", tracer_option=None):
    jax.profiler.start_trace("./profiler_log")


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()
