"""Profiler.

Reference parity: paddle/fluid/platform/profiler.h:127 RecordEvent /
:213 EnableProfiler + python/paddle/fluid/profiler.py:314. TPU-native:
jax.profiler (XPlane) captures real device timelines viewable in
TensorBoard / Perfetto; RecordEvent lowers to jax.profiler.TraceAnnotation
+ jax.named_scope so op metadata reaches the XLA trace, the analogue of
the reference's NVTX/CUPTI annotations.

record_scope is the framework's single instrumentation point with
THREE sinks (see paddle_tpu.observability): the XPlane annotation
above, the bounded host-span ring buffer (chrome://tracing dump), and
the process metrics registry (per-scope seconds/calls, Prometheus
text) — so a scope placed once in the serving engine or the hapi
training loop shows up in the device timeline, the host timeline, and
the dashboard.
"""
import contextlib
import time

import jax

from ..observability import registry as _obs_registry
from ..observability import tracing as _obs_tracing

# framework-wide per-scope accrual (the "dashboard" sink): seconds and
# call count per scope name, in the process-global registry
_span_seconds = _obs_registry.default_registry().counter(
    "host_span_seconds_total",
    "wall seconds accrued per record_scope name", labelnames=("span",))
_span_calls = _obs_registry.default_registry().counter(
    "host_span_calls_total",
    "record_scope completions per scope name", labelnames=("span",))


class RecordEvent:
    """RAII scope annotation (reference: profiler.h:127)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._cm = None

    def __enter__(self):
        self._cm = contextlib.ExitStack()
        self._cm.enter_context(jax.profiler.TraceAnnotation(self.name))
        self._cm.enter_context(jax.named_scope(self.name))
        return self

    def __exit__(self, *exc):
        self._cm.close()
        return False

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__()


@contextlib.contextmanager
def record_scope(name, sink=None):
    """One scope, three sinks. Entering annotates the XLA trace
    (TraceAnnotation + named_scope, visible in a live XPlane capture);
    exiting records the span into the bounded host-span ring buffer
    (observability.default_recorder(), dumpable as a chrome://tracing
    timeline) and accrues seconds + a call count into the process
    metrics registry (observability.default_registry(), scrapeable as
    Prometheus text). An optional ``sink(name, dt)`` callback receives
    the same elapsed seconds — the hook the serving metrics
    (paddle_tpu.serving.metrics) hang their per-engine prefill/decode/
    compile accounting on."""
    t0 = time.perf_counter()
    with RecordEvent(name):
        yield
    dt = time.perf_counter() - t0
    _obs_tracing.default_recorder().record(name, t0, dt)
    _span_seconds.labels(name).inc(dt)
    _span_calls.labels(name).inc()
    if sink is not None:
        sink(name, dt)


class ProfilerState:
    """Reference: paddle.profiler.ProfilerState."""
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3


class ProfilerTarget:
    """Reference: paddle.profiler.ProfilerTarget (CPU/GPU); device
    timelines here come from the XPlane capture, which covers both."""
    CPU = 0
    GPU = 1
    TPU = 2


def make_scheduler(closed=0, ready=0, record=1000000, repeat=0,
                   skip_first=0):
    """Reference: paddle.profiler.make_scheduler — step-state schedule
    [skip_first][closed][ready][record]... repeated."""
    period = closed + ready + record

    def schedule(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        s = step - skip_first
        if repeat > 0 and s >= repeat * period:
            return ProfilerState.CLOSED
        pos = s % period
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == period - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD

    return schedule


def export_chrome_tracing(dir_name, worker_name=None):
    """Reference: paddle.profiler.export_chrome_tracing. The XPlane
    capture already contains a Perfetto/chrome-compatible trace; the
    callback carries the target dir so the Profiler redirects its
    capture there BEFORE the first trace starts (assigning at
    trace-ready time would be too late — the file is already written)."""
    def on_ready(prof):
        return dir_name
    on_ready._export_dir = dir_name
    return on_ready


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler traces
    (reference: python/paddle/profiler/profiler.py). start/stop (or the
    scheduler) capture an XPlane trace under log_dir — the TPU-native
    analogue of the reference's CUPTI DeviceTracer timeline
    (platform/device_tracer.h:43) — viewable in TensorBoard/Perfetto."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 log_dir="./profiler_log", timer_only=False):
        self.log_dir = log_dir
        self.timer_only = timer_only
        if isinstance(scheduler, tuple):
            start, stop = scheduler
            scheduler = make_scheduler(closed=start, ready=0,
                                       record=stop - start, repeat=1)
        self.scheduler = scheduler
        self.on_trace_ready = on_trace_ready
        export_dir = getattr(on_trace_ready, "_export_dir", None)
        if export_dir is not None:
            self.log_dir = export_dir
        self._started = False
        self._tracing = False
        self._step_num = 0
        self._step_times = []
        self._t0 = None

    def _state(self):
        if self.scheduler is None:
            return ProfilerState.RECORD
        return self.scheduler(self._step_num)

    def _sync_trace(self):
        want = (not self.timer_only
                and self._state() in (ProfilerState.RECORD,
                                      ProfilerState.RECORD_AND_RETURN))
        if want and not self._tracing:
            jax.profiler.start_trace(self.log_dir)
            self._tracing = True
        elif not want and self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)

    def start(self):
        self._started = True
        self._sync_trace()
        self._t0 = time.perf_counter()

    def stop(self):
        if self._tracing:
            jax.profiler.stop_trace()
            self._tracing = False
            if self.on_trace_ready is not None:
                self.on_trace_ready(self)
        self._started = False

    def step(self):
        now = time.perf_counter()
        if self._t0 is not None:
            self._step_times.append(now - self._t0)
        self._t0 = now
        self._step_num += 1
        if self._started:
            self._sync_trace()

    def step_info(self, unit=None):
        """Step-time summary string; ``unit`` selects milliseconds
        ("ms", default) or seconds ("s")."""
        unit = "ms" if unit is None else str(unit).lower()
        if unit not in ("ms", "s"):
            raise ValueError(f"unit must be 'ms' or 's', got {unit!r}")
        if not self._step_times:
            return "no steps recorded"
        import numpy as np
        arr = np.asarray(self._step_times[1:] or self._step_times)
        scale = 1000.0 if unit == "ms" else 1.0
        return (f"avg step {arr.mean() * scale:.3f} {unit}, "
                f"min {arr.min() * scale:.3f} {unit}, "
                f"max {arr.max() * scale:.3f} {unit}")

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def summary(self, **kwargs):
        return self.step_info()


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path=None):
    """Legacy fluid.profiler.profiler context (reference:
    python/paddle/fluid/profiler.py:314)."""
    p = Profiler(log_dir=profile_path or "./profiler_log")
    p.start()
    try:
        yield p
    finally:
        p.stop()


def start_profiler(state="All", tracer_option=None):
    jax.profiler.start_trace("./profiler_log")


def stop_profiler(sorted_key=None, profile_path=None):
    jax.profiler.stop_trace()
