"""paddle.device equivalent (reference: python/paddle/device/__init__.py)."""
from ..core.device import (  # noqa: F401
    set_device, get_device, get_place, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, Place, CPUPlace, TPUPlace,
)


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"tpu:{i}" for i in range(device_count())]


def synchronize(device=None):
    import jax
    jax.effects_barrier()


def memory_stats(device=None):
    """Per-device HBM statistics from PjRt (the analogue of the reference
    allocator stats: memory/allocation/allocator_facade.cc + pybind
    memory stat getters). Keys follow jax's device.memory_stats().
    `device`: None (device 0), an int index, a 'tpu:1'-style string, or a
    jax Device."""
    import jax
    dev = _resolve_device(device)
    if dev is None:
        dev = jax.local_devices()[0]
    return dict(dev.memory_stats() or {})


def max_memory_allocated(device=None):
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_allocated(device=None):
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_reserved(device=None):
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    return int(memory_stats(device).get("bytes_in_use", 0))


def empty_cache():
    """Drop python-side references to dead buffers + jit caches (analogue
    of the reference's allocator Release(): allocator_facade.cc). PjRt
    frees HBM when the last reference dies, so gc is the lever here."""
    import gc
    gc.collect()


def _resolve_device(device):
    """None | int index | 'tpu:1'-style string | jax Device -> Device or
    None (same argument forms as memory_stats)."""
    import jax
    if device is None or hasattr(device, "memory_stats"):
        return device
    devs = jax.local_devices()
    if isinstance(device, int):
        idx = device
    elif isinstance(device, str) and device:
        idx = int(device.rsplit(":", 1)[1]) if ":" in device else 0
    else:
        raise ValueError(f"unsupported device spec {device!r}")
    if not 0 <= idx < len(devs):
        raise ValueError(
            f"device index {idx} out of range (have {len(devs)} local "
            "devices)")
    return devs[idx]


def live_array_bytes(device=None):
    """Total bytes of live jax arrays (optionally on one device) — the
    live_buffers surface of the reference's memory stat getters
    (memory/stats.h DeviceMemoryStatCurrentValue), usable on every
    backend including the CPU test mesh where PjRt memory_stats() is
    unavailable. `device` takes the same forms as memory_stats."""
    import jax
    device = _resolve_device(device)
    total = 0
    for arr in jax.live_arrays():
        try:
            for sh in arr.addressable_shards:
                if device is None or sh.device == device:
                    total += sh.data.nbytes
        except Exception:  # deleted/donated arrays
            continue
    return total


class memory_tracker:
    """Context manager measuring live-array memory across a region:

        with paddle.device.memory_tracker() as mt:
            ...training step...
            mt.sample()          # optional mid-region samples
        mt.peak_bytes, mt.delta_bytes

    Peak is the max over enter/samples/exit (host-visible live arrays;
    XLA-internal temps are captured by program_memory_analysis instead).
    Used by the ZeRO and pipeline memory tests; the analogue of the
    reference's peak memory stats (memory/stats.h DeviceMemoryStatPeak).
    """

    def __init__(self, device=None):
        self._device = device
        self.start_bytes = 0
        self.peak_bytes = 0
        self.end_bytes = 0

    def sample(self):
        b = live_array_bytes(self._device)
        self.peak_bytes = max(self.peak_bytes, b)
        return b

    def __enter__(self):
        self.start_bytes = self.sample()
        return self

    def __exit__(self, *exc):
        self.end_bytes = self.sample()
        return False

    @property
    def delta_bytes(self):
        return self.end_bytes - self.start_bytes


def program_memory_analysis(fn, *args, **kwargs):
    """XLA memory analysis of `fn` compiled on these args: dict with
    temp/argument/output/generated-code bytes and their total. This is
    the compile-time equivalent of the reference's allocator peak stats
    — deterministic, available on every backend (the pipeline memory
    test asserts 1F1B flatness with it). `fn` may be a python callable
    (jitted here) or an existing jax.jit object."""
    import jax
    jfn = fn if hasattr(fn, "lower") else jax.jit(fn)
    ma = jfn.lower(*args, **kwargs).compile().memory_analysis()
    out = {
        "temp_bytes": int(ma.temp_size_in_bytes),
        "argument_bytes": int(ma.argument_size_in_bytes),
        "output_bytes": int(ma.output_size_in_bytes),
        "generated_code_bytes": int(ma.generated_code_size_in_bytes),
        "alias_bytes": int(getattr(ma, "alias_size_in_bytes", 0)),
    }
    out["total_bytes"] = (out["temp_bytes"] + out["argument_bytes"]
                          + out["output_bytes"] - out["alias_bytes"])
    return out


def get_cudnn_version():
    """No cuDNN on this backend (reference returns None when absent)."""
    return None


from ..core.device import (  # noqa: E402,F401
    XPUPlace, is_compiled_with_xpu, is_compiled_with_rocm,
    is_compiled_with_npu)


# paddle.device.cuda is a real module (Stream/Event/current_stream/
# synchronize shims); the memory-query API attaches here so reference
# code reading HBM stats through the cuda namespace keeps working.
from . import cuda as cuda  # noqa: E402

cuda.memory_allocated = memory_allocated
cuda.max_memory_allocated = max_memory_allocated
cuda.memory_reserved = memory_reserved
cuda.max_memory_reserved = max_memory_reserved
cuda.empty_cache = empty_cache
