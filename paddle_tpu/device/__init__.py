"""paddle.device equivalent (reference: python/paddle/device/__init__.py)."""
from ..core.device import (  # noqa: F401
    set_device, get_device, get_place, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, Place, CPUPlace, TPUPlace,
)


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"tpu:{i}" for i in range(device_count())]


class cuda:  # namespace shim for paddle.device.cuda users
    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    import jax
    jax.effects_barrier()


def memory_stats(device=None):
    """Per-device HBM statistics from PjRt (the analogue of the reference
    allocator stats: memory/allocation/allocator_facade.cc + pybind
    memory stat getters). Keys follow jax's device.memory_stats().
    `device`: None (device 0), an int index, a 'tpu:1'-style string, or a
    jax Device."""
    import jax
    if device is not None and hasattr(device, "memory_stats"):
        return dict(device.memory_stats() or {})
    devs = jax.local_devices()
    idx = 0
    if isinstance(device, int):
        idx = device
    elif isinstance(device, str) and device:
        idx = int(device.rsplit(":", 1)[1]) if ":" in device else 0
    if not 0 <= idx < len(devs):
        raise ValueError(
            f"device index {idx} out of range (have {len(devs)} local "
            "devices)")
    return dict(devs[idx].memory_stats() or {})


def max_memory_allocated(device=None):
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_allocated(device=None):
    return int(memory_stats(device).get("bytes_in_use", 0))


def max_memory_reserved(device=None):
    return int(memory_stats(device).get("peak_bytes_in_use", 0))


def memory_reserved(device=None):
    return int(memory_stats(device).get("bytes_in_use", 0))
