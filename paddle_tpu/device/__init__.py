"""paddle.device equivalent (reference: python/paddle/device/__init__.py)."""
from ..core.device import (  # noqa: F401
    set_device, get_device, get_place, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, Place, CPUPlace, TPUPlace,
)


def get_all_device_type():
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device():
    return [f"tpu:{i}" for i in range(device_count())]


class cuda:  # namespace shim for paddle.device.cuda users
    @staticmethod
    def device_count():
        return 0

    @staticmethod
    def synchronize(device=None):
        import jax
        (jax.device_put(0) + 0).block_until_ready()


def synchronize(device=None):
    import jax
    jax.effects_barrier()
