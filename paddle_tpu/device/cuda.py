"""paddle.device.cuda module (reference:
python/paddle/device/cuda/__init__.py __all__ = [Stream, Event,
current_stream, synchronize]). On TPU/PjRt, streams are the runtime's
(one compute stream per device, async dispatch); these shims keep
reference code importable and give the memory queries real backends."""
import jax


class Stream:
    """PjRt owns stream scheduling; a Stream is a token object whose
    synchronize() is a device sync (reference: core.CUDAStream)."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        jax.effects_barrier()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        ev = event or Event()
        ev.record(self)
        return ev


class Event:
    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        self._recorded = False

    def record(self, stream=None):
        self._recorded = True

    def query(self):
        return True  # dispatch is async but effects_barrier-ordered

    def synchronize(self):
        jax.effects_barrier()


_current = Stream()


def current_stream(device=None):
    return _current


def synchronize(device=None):
    (jax.device_put(0) + 0).block_until_ready()


def device_count():
    return 0  # no CUDA devices on this backend (TPU path is paddle.device)
