"""KV wire format for prefill/decode disaggregation.

The wire unit is the refcounted paged block: one frame per physical
block, carrying the K and V tiles ``[layers, heads, block_size,
head_dim]`` for that block plus a crc32 digest over both tiles. A
handoff payload bundles the frames covering a request's PROMPT
positions (``ceil(prompt_len / block_size)`` blocks — the partial last
block ships whole; its tail rows are scratch the decode side never
reads, exactly as after a local prefill) together with the prompt
tokens and the first generated token, so the decode tier can bind the
blocks into its own pool and resume the stream at the first decode
step with no recompute.

Everything here is pure host-side numpy over already-fetched tiles:
serialization never touches a pool, and ``deserialize_handoff``
verifies every frame's digest BEFORE assembling arrays — a corrupted
frame raises the typed :class:`KVWireError` with zero pool mutation
on the importing side (the engine only allocates blocks after the
payload decoded clean).

The JSON encoding (base64 tiles) exists for the HTTP transport; the
in-process transport hands the same dict across without a byte copy
beyond serialization itself.
"""
import base64
import binascii
import zlib

import numpy as np

WIRE_VERSION = 1


class KVWireError(RuntimeError):
    """A KV handoff payload failed validation (bad structure, shape /
    dtype drift against the importing pool, or a frame whose digest
    does not match its tiles). Raised BEFORE any pool mutation: an
    importer that sees this error has a bit-identical pool to one that
    never saw the payload."""


class KVHandoff:
    """A decoded handoff: stacked block tiles plus the resume facts.

    ``k``/``v`` are ``[layers, n_blocks, heads, block_size, head_dim]``
    host arrays in block-table row order; ``wire_bytes`` is the raw
    tile payload size (both caches, pre-base64) — the transfer-cost
    fact the perf ledger prices per token.
    """

    __slots__ = ("prompt", "first_token", "block_size", "k", "v",
                 "wire_bytes", "trace")

    def __init__(self, prompt, first_token, block_size, k, v,
                 wire_bytes, trace=None):
        self.prompt = prompt
        self.first_token = int(first_token)
        self.block_size = int(block_size)
        self.k = k
        self.v = v
        self.wire_bytes = int(wire_bytes)
        # optional distributed-trace baggage ({"traceparent",
        # "baggage"} dict or None) — NEVER validated here: a corrupted
        # trace field must not refuse a payload whose tiles verified
        # clean (the importer coerces, minting a local root on garbage)
        self.trace = trace

    @property
    def n_blocks(self):
        return self.k.shape[1]


def blocks_for_prompt(prompt_len, block_size):
    """How many leading row blocks a prompt's K/V occupies (the
    partial last block counts whole)."""
    if prompt_len < 1:
        raise ValueError(f"prompt_len must be >= 1, got {prompt_len}")
    return -(-int(prompt_len) // int(block_size))


def serialize_handoff(k_tiles, v_tiles, prompt, first_token,
                      trace=None):
    """Pack prompt-covering block tiles into a JSON-safe handoff dict.

    ``k_tiles``/``v_tiles``: ``[layers, n_blocks, heads, block_size,
    head_dim]`` host arrays (the exporter slices them off its pool in
    block-table row order). Serialization is pure — no pool access,
    no device work — so the transfer loop stays off the compiled hot
    path by construction.
    """
    k_tiles = np.ascontiguousarray(k_tiles)
    v_tiles = np.ascontiguousarray(v_tiles)
    if k_tiles.ndim != 5 or k_tiles.shape != v_tiles.shape:
        raise ValueError(
            f"k/v tiles must be identical 5-D [layers, n_blocks, "
            f"heads, block_size, head_dim] arrays, got "
            f"{k_tiles.shape} / {v_tiles.shape}")
    if k_tiles.dtype != v_tiles.dtype:
        raise ValueError(
            f"k/v tile dtype mismatch: {k_tiles.dtype} vs "
            f"{v_tiles.dtype}")
    layers, n_blocks, heads, block_size, head_dim = k_tiles.shape
    prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
    if not prompt:
        raise ValueError("empty prompt")
    need = blocks_for_prompt(len(prompt), block_size)
    if n_blocks != need:
        raise ValueError(
            f"{len(prompt)} prompt tokens need {need} blocks of "
            f"{block_size}, got {n_blocks} tiles")
    frames = []
    for i in range(n_blocks):
        kb = np.ascontiguousarray(k_tiles[:, i]).tobytes()
        vb = np.ascontiguousarray(v_tiles[:, i]).tobytes()
        frames.append({
            "k": base64.b64encode(kb).decode("ascii"),
            "v": base64.b64encode(vb).decode("ascii"),
            "digest": zlib.crc32(vb, zlib.crc32(kb)) & 0xFFFFFFFF,
        })
    tile_bytes = int(k_tiles[:, 0].nbytes)
    payload = {
        "version": WIRE_VERSION,
        "dtype": str(np.dtype(k_tiles.dtype)),
        "tile_shape": [int(layers), int(heads), int(block_size),
                       int(head_dim)],
        "tile_bytes": tile_bytes,
        "prompt": prompt,
        "first_token": int(first_token),
        "frames": frames,
    }
    if trace is not None:
        # distributed tracing: the request's context rides the
        # handoff so the decode-tier import joins the SAME trace
        # (TraceContext dict form; absent = pre-trace exporter)
        payload["trace"] = trace if isinstance(trace, dict) \
            else trace.as_dict()
    return payload


def payload_wire_bytes(payload):
    """Raw K+V tile bytes a payload carries (pre-base64) — the router's
    wire-accounting read, cheap enough to call without deserializing."""
    try:
        return 2 * int(payload["tile_bytes"]) * len(payload["frames"])
    except (KeyError, TypeError) as e:
        raise KVWireError(f"malformed handoff payload: {e!r}") from None


def _resolve_dtype(name):
    try:
        return np.dtype(name)
    except TypeError:
        # extension dtypes (bfloat16 & friends) register with numpy
        # only once ml_dtypes is imported — resolve lazily so this
        # module never imports jax/ml_dtypes for the float32 case
        try:
            import ml_dtypes
            return np.dtype(getattr(ml_dtypes, str(name)))
        except (ImportError, AttributeError, TypeError):
            raise KVWireError(
                f"unknown tile dtype {name!r}") from None


def deserialize_handoff(payload):
    """Decode + verify a handoff payload into a :class:`KVHandoff`.

    Every frame's crc32 is checked against its decoded tiles BEFORE
    any array is assembled; structural problems (missing fields, wrong
    version, tile-count/prompt-length disagreement, bad base64) and
    digest mismatches all raise :class:`KVWireError` — the caller's
    pool is untouched either way.
    """
    if not isinstance(payload, dict):
        raise KVWireError(
            f"handoff payload must be a dict, got "
            f"{type(payload).__name__}")
    if payload.get("version") != WIRE_VERSION:
        raise KVWireError(
            f"unsupported wire version {payload.get('version')!r} "
            f"(this importer speaks {WIRE_VERSION})")
    try:
        dtype = _resolve_dtype(payload["dtype"])
        layers, heads, block_size, head_dim = (
            int(d) for d in payload["tile_shape"])
        prompt = [int(t) for t in payload["prompt"]]
        first_token = int(payload["first_token"])
        frames = payload["frames"]
    except (KeyError, TypeError, ValueError) as e:
        raise KVWireError(
            f"malformed handoff payload: {e!r}") from None
    if not prompt:
        raise KVWireError("handoff payload has an empty prompt")
    need = blocks_for_prompt(len(prompt), block_size)
    if not isinstance(frames, list) or len(frames) != need:
        raise KVWireError(
            f"{len(prompt)} prompt tokens need {need} frames of "
            f"block_size {block_size}, payload has "
            f"{len(frames) if isinstance(frames, list) else frames!r}")
    tile_shape = (layers, heads, block_size, head_dim)
    tile_bytes = int(np.prod(tile_shape)) * dtype.itemsize
    k_list, v_list = [], []
    wire_bytes = 0
    for i, frame in enumerate(frames):
        try:
            kb = base64.b64decode(frame["k"], validate=True)
            vb = base64.b64decode(frame["v"], validate=True)
            digest = int(frame["digest"])
        except (KeyError, TypeError, ValueError,
                binascii.Error) as e:
            raise KVWireError(
                f"malformed frame {i}: {e!r}") from None
        if len(kb) != tile_bytes or len(vb) != tile_bytes:
            raise KVWireError(
                f"frame {i} tile size {len(kb)}/{len(vb)} != expected "
                f"{tile_bytes} for shape {tile_shape} {dtype}")
        got = zlib.crc32(vb, zlib.crc32(kb)) & 0xFFFFFFFF
        if got != digest & 0xFFFFFFFF:
            raise KVWireError(
                f"frame {i} digest mismatch: payload says "
                f"{digest & 0xFFFFFFFF:#010x}, tiles hash "
                f"{got:#010x} — frame corrupted in transit, "
                f"import refused")
        k_list.append(np.frombuffer(kb, dtype).reshape(tile_shape))
        v_list.append(np.frombuffer(vb, dtype).reshape(tile_shape))
        wire_bytes += len(kb) + len(vb)
    k = np.stack(k_list, axis=1)
    v = np.stack(v_list, axis=1)
    return KVHandoff(prompt, first_token, block_size, k, v,
                     wire_bytes, trace=payload.get("trace"))
