"""Slot-pooled static-shape KV cache.

The pool owns ONE pair of cache arrays shaped
``[layers, num_slots, heads, max_len, head_dim]`` for K and V. Slots are
the unit of admission: a request claims a slot at prefill, decodes in
place, and frees the slot the step it finishes — a waiting request then
claims it mid-flight. Because the arrays never change shape, the jitted
decode step runs at ONE fixed signature forever (vLLM's slot/paged
insight collapsed to slot granularity: no paging, one contiguous region
per slot, which is the right trade for XLA's static-shape world).

Slot recycling never needs a cache wipe: prefill overwrites positions
``0..bucket-1`` of the claimed slot and the per-slot length mask
(ops/attention.cached_slot_attention) hides every position beyond the
request's live prefix, so a recycled slot is indistinguishable from a
fresh one (tests/test_serving.py pins this).
"""
import heapq

import jax.numpy as jnp


class SlotKVPool:
    """Free-list allocator over the pooled cache arrays.

    ``kc``/``vc`` are rebound (``rebind``) by the engine after every
    compiled call: the executables return the new arrays, and on
    donating backends (TPU/GPU) the INPUT buffers were consumed in
    place — routing the swap through the pool keeps it the single
    owner of the live buffers. The pool itself only tracks WHICH slots
    are live and hands out the lowest free index via a heap
    (deterministic allocation keeps runs reproducible).
    """

    def __init__(self, num_slots, num_layers, num_heads, max_len,
                 head_dim, dtype=jnp.float32):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        self.num_slots = int(num_slots)
        self.max_len = int(max_len)
        shape = (int(num_layers), self.num_slots, int(num_heads),
                 self.max_len, int(head_dim))
        self.kc = jnp.zeros(shape, dtype)
        self.vc = jnp.zeros(shape, dtype)
        self._free = list(range(self.num_slots))  # heap: lowest first
        self._owner = {}                          # slot -> request id
        self._quarantined = set()  # excluded from admission (resilience)
        self.reuse_count = 0   # acquisitions of a previously-used slot
        self._ever_used = set()

    @property
    def free_count(self):
        return len(self._free)

    @property
    def occupancy(self):
        """Fraction of slots currently owned by live requests
        (quarantined slots are neither free nor occupied)."""
        return len(self._owner) / self.num_slots

    @property
    def quarantined(self):
        """Slots excluded from admission (sorted)."""
        return sorted(self._quarantined)

    def quarantine(self, slot):
        """Exclude a FREE slot from future admission (the engine's
        repeated-same-slot-failure response). Raises when the slot is
        live — quarantine happens after rollback released it."""
        if slot in self._owner:
            raise ValueError(f"slot {slot} is live; release it first")
        if slot in self._quarantined:
            return
        self._free.remove(slot)
        heapq.heapify(self._free)
        self._quarantined.add(slot)

    def unquarantine_all(self):
        """Return every quarantined slot to the free heap (supervisor
        restart / operator reset)."""
        for slot in sorted(self._quarantined):
            heapq.heappush(self._free, slot)
        self._quarantined.clear()

    def acquire(self, owner):
        """Claim the lowest free slot for ``owner``; None when full."""
        if not self._free:
            return None
        slot = heapq.heappop(self._free)
        self._owner[slot] = owner
        if slot in self._ever_used:
            self.reuse_count += 1
        self._ever_used.add(slot)
        return slot

    def release(self, slot):
        if slot not in self._owner:
            raise ValueError(f"slot {slot} is not live")
        del self._owner[slot]
        heapq.heappush(self._free, slot)

    def owner_of(self, slot):
        return self._owner.get(slot)

    def rebind(self, kc, vc):
        """Swap in the cache arrays a compiled call returned. With
        buffer donation the previous arrays are already invalid, so
        every shape/dtype drift must be caught HERE, before a stale or
        mismatched buffer reaches the next AOT executable."""
        if kc.shape != self.kc.shape or vc.shape != self.vc.shape:
            raise ValueError(
                f"rebind shape drift: got {kc.shape}/{vc.shape}, pool "
                f"owns {self.kc.shape}")
        if kc.dtype != self.kc.dtype or vc.dtype != self.vc.dtype:
            raise ValueError(
                f"rebind dtype drift: got {kc.dtype}/{vc.dtype}, pool "
                f"owns {self.kc.dtype}")
        self.kc, self.vc = kc, vc

    def nbytes(self):
        return int(self.kc.nbytes + self.vc.nbytes)
