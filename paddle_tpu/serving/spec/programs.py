"""AOT-compilable k-token verify programs (speculative decoding,
Leviathan et al.): ONE more program flavor per pool that runs the
decode forward over ``[slots, k+1]`` positions in a single dispatch —
the slot's last accepted token plus its k drafted continuations — so
the HBM-bound parameter + KV read every decode dispatch pays is
amortized over up to k+1 emitted tokens.

  ``spec_verify(params, toks [S], pos [S], drafts [S, k], dlen [S],
                kc, vc)``
      -> (out [S, k+1], accepted [S], toks', pos', kc, vc)

  ``paged_spec_verify(params, toks [S], pos [S], drafts [S, k],
                      dlen [S], tables [S, MB], kc, vc)``
      -> (out [S, k+1], accepted [S], toks', pos', kc, vc)

Shapes are FIXED: drafts pad to width k and ``dlen`` carries each
slot's real draft length (0 = this slot behaves exactly like a plain
decode step inside the verify program — the per-slot fallback costs
no extra program). ``out[s, i]`` is the greedy argmax after consuming
input position i; draft i is accepted iff it equals ``out[s, i]`` and
every earlier draft was accepted (longest-accepted-prefix), so
``accepted = sum(cumprod(match))`` on device, the next chained token
is the "bonus" ``out[s, accepted]``, and positions advance by
``accepted + 1`` — toks'/pos' chain device-side exactly like the
plain decode step, and the engine reads (out, accepted) back at
harvest to emit 1..k+1 tokens.

Greedy parity with generate() is by construction: query i attends
(per-query causal mask, ops.attention.cached_slot_block_attention)
over the live prefix plus candidates 0..i only, so its logits are
conditioned purely on tokens that are accepted whenever position i's
output is harvested. Rejected-tail K/V rows land in the cache but are
invisible and then legitimately overwritten: the next dispatch writes
its rows before attending (the same recycled-slot/parked-row
invariant the chunked-prefill program pins).

Write discipline per pool:

  * legacy — a windowed read-merge-write per slot: the t-row window
    starting at ``min(pos, C - t)`` is read, rows whose global
    position is a real candidate position (< C) take the new K/V,
    rows below keep their current (historical) values, and positions
    past the end are dropped — parked slots (pos >= C) write nothing
    at all, strictly safer than plain decode's end-clamped write;
  * paged — PR 7's whole-position ``wpos`` clamp per candidate row,
    with rows past the slot's addressable range routed to the
    reserved trash block (index 0), so a parked/overflowing slot's
    stray rows land in garbage instead of cycling over live blocks.
"""


def build_spec_verify_fn(cfg, num_slots, cache_len, k):
    """The legacy-pool verify program for a GPT decode config. Pure
    and shape-stable; the engine AOT-compiles it ONCE (key
    ``("spec_verify",)``) alongside the plain decode."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ...ops import attention as attn_ops
    from ...text.models import _decode_forward_builder

    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    hidden = cfg.hidden_size
    ln, _ = _decode_forward_builder(nh, hd, hidden)
    C = int(cache_len)
    t = int(k) + 1
    assert 1 <= t <= C, f"spec_k+1 ({t}) must fit the cache ({C})"

    def write_slot_block(cache_l, new, pos):
        # cache_l [S, nh, C, hd]; new [S, nh, t, hd]; pos [S]: each
        # slot merges its t candidate rows into the window starting at
        # min(pos, C - t) — rows below pos keep history, rows past
        # C-1 are dropped (parked slots write nothing)
        z = jnp.int32(0)

        def one(c, n, p):
            wstart = jnp.minimum(p, jnp.int32(C - t))
            d = p - wstart                      # >= 0; >= t when parked
            win = lax.dynamic_slice(c, (z, wstart, z), (nh, t, hd))
            rows = jnp.arange(t)
            shifted = jnp.take(n, jnp.maximum(rows - d, 0), axis=1)
            merged = jnp.where((rows >= d)[None, :, None], shifted,
                               win)
            return lax.dynamic_update_slice(c, merged, (z, wstart, z))

        return jax.vmap(one)(cache_l, new, pos)

    def spec_verify(params, toks, pos, drafts, dlen, kc, vc):
        S = toks.shape[0]
        tok_blk = jnp.concatenate([toks[:, None], drafts], axis=1)
        qpos = pos[:, None] + jnp.arange(t)[None, :]     # [S, t]
        x = params["wemb"][tok_blk] + params["pemb"][
            jnp.minimum(qpos, params["pemb"].shape[0] - 1)]

        def body(carry, inp):
            x = carry
            p, kcl, vcl = inp
            h_ = ln(x, p["ln1_w"], p["ln1_b"])
            qkv = h_ @ p["qkv_w"] + p["qkv_b"]
            qkv = qkv.reshape(S, t, 3, nh, hd).transpose(2, 0, 3, 1, 4)
            q, k_, v = qkv[0], qkv[1], qkv[2]     # [S, nh, t, hd]
            kcl = write_slot_block(kcl, k_, pos)
            vcl = write_slot_block(vcl, v, pos)
            o = attn_ops.cached_slot_block_attention(q, kcl, vcl,
                                                     qpos)
            o = o.transpose(0, 2, 1, 3).reshape(S, t, hidden)
            x = x + (o @ p["out_w"] + p["out_b"])
            h2 = ln(x, p["ln2_w"], p["ln2_b"])
            m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"],
                            approximate=True)
            return x + (m @ p["fc2_w"] + p["fc2_b"]), (kcl, vcl)

        x, (kc, vc) = lax.scan(body, x, (params["stacked"], kc, vc))
        logits = ln(x, params["lnf_w"], params["lnf_b"]) \
            @ params["head"]                       # [S, t, vocab]
        out = jnp.argmax(logits, -1).astype(jnp.int32)   # [S, t]
        return _accept(jnp, out, drafts, dlen, pos, kc, vc)

    return spec_verify


def build_paged_spec_verify_fn(cfg, num_slots, block_size, num_blocks,
                               blocks_per_slot, k):
    """The paged-pool verify program (key ``("paged_spec_verify",)``):
    same math, cache addressed through the fixed-shape block table
    with candidate rows scattered straight into each slot's privately
    owned blocks (decode positions are never inside shared-prefix
    blocks) and overflow rows trash-routed."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ...ops import attention as attn_ops
    from ...text.models import _decode_forward_builder

    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    hidden = cfg.hidden_size
    ln, _ = _decode_forward_builder(nh, hd, hidden)
    BS = int(block_size)
    MB = int(blocks_per_slot)
    C = MB * BS
    t = int(k) + 1
    assert 1 <= t <= C, f"spec_k+1 ({t}) must fit the slot row ({C})"

    def paged_spec_verify(params, toks, pos, drafts, dlen, tables, kc,
                          vc):
        S = toks.shape[0]
        tok_blk = jnp.concatenate([toks[:, None], drafts], axis=1)
        qpos = pos[:, None] + jnp.arange(t)[None, :]     # [S, t]
        x = params["wemb"][tok_blk] + params["pemb"][
            jnp.minimum(qpos, params["pemb"].shape[0] - 1)]
        # PR-7 wpos discipline, per candidate row: clamp the WHOLE
        # position, then route rows past the slot's addressable range
        # to the trash block so parked/overflowing slots never touch a
        # live block (plain decode pins to the private last entry; with
        # t rows that would collide, so garbage goes to garbage)
        valid = qpos <= jnp.int32(C - 1)                 # [S, t]
        wpos = jnp.minimum(qpos, jnp.int32(C - 1))
        col = wpos // jnp.int32(BS)
        bidx = jnp.take_along_axis(tables, col, axis=1)  # [S, t]
        bidx = jnp.where(valid, bidx, jnp.int32(0))
        off = wpos % jnp.int32(BS)

        def body(carry, inp):
            x = carry
            p, kcl, vcl = inp
            h_ = ln(x, p["ln1_w"], p["ln1_b"])
            qkv = h_ @ p["qkv_w"] + p["qkv_b"]
            qkv = qkv.reshape(S, t, 3, nh, hd).transpose(2, 0, 3, 1, 4)
            q, k_, v = qkv[0], qkv[1], qkv[2]     # [S, nh, t, hd]
            # advanced-index scatter: [S, t] block rows x offsets take
            # [S, t, nh, hd] values
            kcl = kcl.at[bidx, :, off].set(k_.transpose(0, 2, 1, 3))
            vcl = vcl.at[bidx, :, off].set(v.transpose(0, 2, 1, 3))
            o = attn_ops.cached_paged_block_attention(q, kcl, vcl,
                                                      tables, qpos)
            o = o.transpose(0, 2, 1, 3).reshape(S, t, hidden)
            x = x + (o @ p["out_w"] + p["out_b"])
            h2 = ln(x, p["ln2_w"], p["ln2_b"])
            m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"],
                            approximate=True)
            return x + (m @ p["fc2_w"] + p["fc2_b"]), (kcl, vcl)

        x, (kc, vc) = lax.scan(body, x, (params["stacked"], kc, vc))
        logits = ln(x, params["lnf_w"], params["lnf_b"]) \
            @ params["head"]                       # [S, t, vocab]
        out = jnp.argmax(logits, -1).astype(jnp.int32)   # [S, t]
        return _accept(jnp, out, drafts, dlen, pos, kc, vc)

    return paged_spec_verify


def _accept(jnp, out, drafts, dlen, pos, kc, vc):
    """Device-side longest-accepted-prefix: draft i counts iff it is a
    real draft (i < dlen) AND matches the model's greedy choice AND
    every earlier draft counted; the chained next token is the bonus
    ``out[s, accepted]`` and positions advance by accepted + 1."""
    k = drafts.shape[1]
    m = (out[:, :k] == drafts) & \
        (jnp.arange(k)[None, :] < dlen[:, None])
    # x64 note: jnp.sum widens int32 reductions to int64 when x64 is
    # on (this package enables it); pos/toks must stay int32 so the
    # chained outputs feed the next dispatch's compiled signature
    accepted = jnp.sum(jnp.cumprod(m.astype(jnp.int32), axis=1),
                       axis=1).astype(jnp.int32)          # [S]
    nxt = jnp.take_along_axis(out, accepted[:, None], axis=1)[:, 0]
    return (out, accepted, nxt,
            (pos + accepted + 1).astype(jnp.int32), kc, vc)
