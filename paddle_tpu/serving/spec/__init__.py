"""Self-drafting speculative decoding on the slot pool (ROADMAP open
item #2): an n-gram/prompt-lookup drafter proposes up to k tokens per
active slot from the slot's own context (no second model), and ONE
extra AOT program flavor per pool verifies all k+1 positions in a
single fixed-shape dispatch — amortizing the HBM-bound parameter + KV
read that plain decode pays per token. Greedy streams stay bit-exact
with generate() by construction (longest-accepted-prefix harvest over
per-query causally-masked logits); acceptance collapse falls back to
plain decode per slot via an EWMA gate.

Engine knobs: ``ServingConfig(speculative=True, spec_k=4,
spec_min_accept=...)`` / env ``PADDLE_SPEC_DECODE=1``. Greedy-only in
this iteration (speculation x sampling is rejected at config time).
"""
from .decoder import SpecDecoder  # noqa: F401
from .drafter import NGramDrafter  # noqa: F401
from .programs import (  # noqa: F401
    build_paged_spec_verify_fn, build_spec_verify_fn,
)
