"""Self-drafting n-gram / prompt-lookup proposer (Saxena, "Prompt
Lookup Decoding"): no second model — each slot's own context
(``prefill_ids`` = prompt + everything emitted so far) is the draft
source. The drafter keeps, per slot, a bounded index mapping the last
n tokens to the position right after their most recent PRIOR
occurrence; a proposal is simply the k tokens that followed that
occurrence. It shines exactly where the bench's traffic lives:
structured/repetitive generations (greedy tiny-model decoding locks
into cycles; real models repeat boilerplate, code idioms, entity
names) and shared prefixes.

Design constraints the serving engine imposes:

  * **bounded memory** — per-slot index entries are capped
    (``max_entries``, FIFO eviction) and per-slot history is naturally
    bounded by the slot's cache capacity; the shared prompt index is a
    capped LRU. Adversarial token streams cannot grow state past the
    caps (tests/test_spec.py proves it);
  * **incremental** — ``sync()`` indexes only the tokens appended
    since the last call (O(new tokens * ngram orders) per step, not
    O(context));
  * **radix-cache-aware sharing** — prompt n-grams feed a SHARED
    content-keyed index: two requests with the same (radix-shareable)
    prompt prefix contribute identical entries, so the second request
    drafts from the first's statistics immediately, and a seen-prompt
    fingerprint set skips re-indexing work for exact repeats — the
    host-side analogue of the paged pool's radix prefix reuse;
  * **deterministic** — pure dict/list machinery, most-recent-match
    policy, no randomness: identical token streams yield identical
    proposals (the chaos sweep's bit-exact replay depends on this).

Proposals are returned unpadded (the SpecDecoder pads to the fixed
``[S, k]`` draft width the AOT verify program requires).
"""
from collections import OrderedDict


class _SlotIndex:
    """One slot's incremental n-gram index over its token history."""

    __slots__ = ("history", "index", "max_entries")

    def __init__(self, max_entries):
        self.history = []
        # ngram tuple -> (prev_start, last_start): positions right
        # AFTER the two most recent occurrences. The suffix n-gram of
        # the history always maps its own (useless, empty-continuation)
        # occurrence to last_start == len(history); prev_start keeps
        # the one a proposal actually wants.
        self.index = OrderedDict()
        self.max_entries = max_entries

    def extend(self, tokens, orders):
        h = self.history
        idx = self.index
        for tok in tokens:
            h.append(int(tok))
            end = len(h)
            for n in orders:
                if end < n:
                    continue
                key = tuple(h[end - n:end])
                old = idx.pop(key, None)
                idx[key] = (old[1] if old else None, end)
                if len(idx) > self.max_entries:
                    idx.popitem(last=False)

    def lookup(self, orders):
        """Continuation-start position for the history's freshest
        matching suffix n-gram (longest order first), or None."""
        h = self.history
        end = len(h)
        for n in orders:
            if end < n:
                continue
            hit = self.index.get(tuple(h[end - n:end]))
            if hit is None:
                continue
            prev, last = hit
            p = last if last < end else prev
            if p is not None and p < end:
                return p
        return None


class NGramDrafter:
    """Bounded, incremental, radix-aware prompt-lookup draft index.

    ``k``            draft width (max tokens proposed per call);
    ``ngram_max`` / ``ngram_min``
                     suffix n-gram orders tried, longest first
                     (longer matches draft more reliably);
    ``max_entries``  per-slot index cap (FIFO eviction);
    ``shared_entries``
                     cap of the cross-request shared prompt index
                     (LRU) and of the seen-prompt fingerprint set.
    """

    def __init__(self, k, ngram_max=3, ngram_min=2, max_entries=4096,
                 shared_entries=16384):
        if k < 1:
            raise ValueError(f"draft width k must be >= 1, got {k}")
        if ngram_min < 1 or ngram_max < ngram_min:
            raise ValueError("need 1 <= ngram_min <= ngram_max")
        self.k = int(k)
        self.orders = tuple(range(int(ngram_max), int(ngram_min) - 1,
                                  -1))
        self.max_entries = int(max_entries)
        self.shared_entries = int(shared_entries)
        self._slots = {}          # slot -> (rid, _SlotIndex)
        self._shared = OrderedDict()   # ngram -> continuation tuple
        self._seen_prompts = OrderedDict()  # prompt fingerprint -> True

    # -- binding / incremental sync --------------------------------

    def sync(self, slot, rid, tokens):
        """Bind (slot, rid) if new, then index any tokens appended
        since the last sync. ``tokens`` is the request's full
        prompt-plus-generated list; only the unseen tail is processed.
        On first bind the PROMPT part also feeds the shared index
        (skipped entirely for an exactly-repeated prompt — its
        n-grams are already there)."""
        bound = self._slots.get(slot)
        if bound is None or bound[0] != rid:
            st = _SlotIndex(self.max_entries)
            self._slots[slot] = (rid, st)
            self._index_shared_prompt(tokens)
        else:
            st = bound[1]
        done = len(st.history)
        if len(tokens) > done:
            st.extend(tokens[done:], self.orders)

    def release(self, slot):
        self._slots.pop(slot, None)

    def _index_shared_prompt(self, prompt):
        fp = hash(tuple(int(t) for t in prompt))
        if fp in self._seen_prompts:
            self._seen_prompts.move_to_end(fp)
            return
        self._seen_prompts[fp] = True
        if len(self._seen_prompts) > self.shared_entries:
            self._seen_prompts.popitem(last=False)
        n_min = self.orders[-1]
        toks = [int(t) for t in prompt]
        for end in range(n_min, len(toks)):
            for n in self.orders:
                if end < n:
                    continue
                cont = tuple(toks[end:end + self.k])
                if not cont:
                    continue
                key = tuple(toks[end - n:end])
                self._shared.pop(key, None)
                self._shared[key] = cont
                if len(self._shared) > self.shared_entries:
                    self._shared.popitem(last=False)

    # -- proposals --------------------------------------------------

    def propose(self, slot, width=None):
        """Up to ``min(k, width)`` draft tokens continuing this slot's
        context, or [] when no n-gram matches. Own-context matches win
        (freshest statistics); the shared prompt index is the
        fallback for requests that haven't generated enough context
        of their own yet."""
        bound = self._slots.get(slot)
        if bound is None:
            return []
        st = bound[1]
        w = self.k if width is None else min(self.k, int(width))
        if w < 1:
            return []
        p = st.lookup(self.orders)
        if p is not None:
            return st.history[p:p + w]
        h = st.history
        end = len(h)
        for n in self.orders:
            if end < n:
                continue
            cont = self._shared.get(tuple(h[end - n:end]))
            if cont:
                return list(cont[:w])
        return []

    # -- introspection (tests; bounded-memory proof) ----------------

    def index_sizes(self):
        """{slot: per-slot index entries} plus the shared index size —
        every number is bounded by the caps above by construction."""
        sizes = {slot: len(st.index)
                 for slot, (_, st) in self._slots.items()}
        sizes["shared"] = len(self._shared)
        sizes["seen_prompts"] = len(self._seen_prompts)
        return sizes
