"""Host-side speculation coordinator: owns the drafter, the per-slot
fixed-shape draft buffers the AOT verify program consumes, and the
EWMA acceptance gate that falls a request back to plain decode when
its drafts stop landing (the PR-7 policy-feedback pattern: observe a
live signal, gate a scheduling decision on its smoothed value).

The engine calls ``propose(snapshot)`` once per decode-capable step
(AFTER harvesting any in-flight results — speculation drafts from the
request's last HARVESTED token, so the engine's spec schedule
harvests before proposing; device toks/pos still chain device-side)
and ``observe(...)`` once per verified slot at harvest.

Gating, per request:

  * draft width is capped at ``min(k, remaining - 1)`` — never draft
    past ``max_new_tokens`` (the +1 is the verify step's guaranteed
    bonus token), so a finishing request degrades to plain decode for
    free instead of shipping tokens the harvest would discard;
  * an EWMA of per-verify acceptance (accepted / drafted, seeded
    optimistically at 1.0) below ``min_accept`` stops proposals for
    that request — the verify program treats a zero-length draft as a
    plain decode for that slot, and a step where NO slot drafts is
    dispatched on the plain decode program outright;
  * EWMA state is a bounded LRU (finished requests age out; no
    per-request cleanup hook needed).
"""
from collections import OrderedDict

import numpy as np

from .drafter import NGramDrafter

_EWMA_KEEP = 4096


class SpecDecoder:
    def __init__(self, num_slots, k, min_accept, ewma_alpha=0.3,
                 drafter=None):
        self.num_slots = int(num_slots)
        self.k = int(k)
        self.min_accept = float(min_accept)
        self.alpha = float(ewma_alpha)
        self.drafter = drafter if drafter is not None \
            else NGramDrafter(k)
        self._ewma = OrderedDict()        # rid -> smoothed acceptance

    # -- per-step proposal -----------------------------------------

    def propose(self, snapshot):
        """snapshot: {slot: Request} (decode-eligible slots only).
        Returns (drafts [S, k] int32, dlen [S] int32, drafted) with
        drafted = {slot: n} for slots given a non-empty draft — empty
        means the engine should dispatch the plain decode program."""
        drafts = np.zeros((self.num_slots, self.k), np.int32)
        dlen = np.zeros((self.num_slots,), np.int32)
        drafted = {}
        for slot, req in snapshot.items():
            ids = req.prefill_ids
            self.drafter.sync(slot, req.rid, ids)
            if req.inflight or not req.generated:
                # the device-side chained token is not in prefill_ids
                # yet (freshly prefilled slot, or a result still in
                # flight) — a draft here would extend the wrong token
                continue
            width = req.max_new_tokens - len(req.generated) - 1
            if width < 1:
                continue
            if self._ewma.get(req.rid, 1.0) < self.min_accept:
                continue
            prop = self.drafter.propose(slot, width=width)
            if not prop:
                continue
            n = len(prop)
            drafts[slot, :n] = prop
            dlen[slot] = n
            drafted[slot] = n
        return drafts, dlen, drafted

    # -- harvest feedback ------------------------------------------

    def observe(self, rid, drafted, accepted):
        """Fold one verify outcome into the request's acceptance EWMA
        (only meaningful when it actually drafted)."""
        if drafted <= 0:
            return
        rate = accepted / drafted
        old = self._ewma.pop(rid, 1.0)
        self._ewma[rid] = self.alpha * rate + (1 - self.alpha) * old
        while len(self._ewma) > _EWMA_KEEP:
            self._ewma.popitem(last=False)

    def acceptance_ewma(self, rid):
        return self._ewma.get(rid, 1.0)

    def reset(self):
        """Supervisor restart: drop all draft state (replay rebuilds
        context from the journaled prefill_ids bit-exactly)."""
        self.drafter = NGramDrafter(
            self.k, max_entries=self.drafter.max_entries,
            shared_entries=self.drafter.shared_entries)
        self._ewma.clear()
