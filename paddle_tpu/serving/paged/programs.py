"""AOT-compilable prefill/decode programs over the paged cache.

Same decode math as ``text.models.build_serving_fns`` (both reuse
``_decode_forward_builder``; greedy parity with ``generate()`` is by
construction), with the cache addressed through the fixed-shape block
table instead of a slot-contiguous region:

  ``paged_prefill(params, tokens [1, B], tail_len, start, slot, final,
                  bt_row [MB], toks [S], pos [S], kc, vc[, samp...])``
      One request's UNCACHED TAIL (or, under chunked prefill, one
      CHUNK of it) prefills in one dispatch: the slot's MB blocks
      gather into a position-ordered contiguous view
      ``[L, 1, nh, MB*BS, hd]`` (view index == cache position, so the
      shared forward_t attends over the cached prefix below ``start``
      exactly as if this slot had prefilled it itself), the tail's K/V
      writes land at ``start..start+B``, and the view scatters back
      block-by-block. ``start``, ``tail_len`` and ``final`` are TRACED
      scalars: every (prefix length, tail length, chunk index) triple
      reuses the one compiled program per tail bucket B — prefix AND
      chunk variety cost zero compiles. Only a ``final != 0`` dispatch
      emits the first token and sets ``pos[slot] = start + tail_len``;
      interior chunk dispatches PARK the slot at the row's last
      addressable position instead (``MB*BS - 1`` — trash-backed or
      legitimately overwritten before its length mask exposes it), so
      the decode steps interleaving between chunks never write inside
      prompt rows earlier chunks filled.

  ``paged_decode(params, toks [S], pos [S], tables [S, MB], kc, vc
                 [, samp...])``
      One fused program advancing every slot a token: each slot writes
      its new K/V row into block ``tables[s, pos//BS]`` at offset
      ``pos % BS`` (always a privately-owned block: decode positions
      are >= prompt_len and only full-prompt blocks are ever shared),
      then attends through ``ops.attention.cached_paged_attention``
      under the per-slot length mask. The write position is clamped to
      the row's last entry (``MB*BS - 1``): parked/released slots'
      positions keep incrementing past the row, and clamping the whole
      position (not just the block column) pins their stray write to
      that one entry — which is always private, never a shared prefix
      block (see the invariant asserted in ``pool.acquire``) — instead
      of cycling across block MB-1's offsets or gathering out of
      bounds.

Scatter/gather safety: table-row padding and released rows point at
the reserved trash block, so pad-entry writes land in garbage, and the
length mask keeps garbage reads at exactly-zero softmax weight — the
same recycled-slot invariant the legacy pool pins, at block granularity.

``sampling=True`` threads per-slot sampling parameters (seeds / temps
/ top-k / top-p — serving.sched.sampling) through both programs; the
greedy path is the default and keeps the original signatures.

``attn_kernel=True`` swaps the decode program's attention for the
Pallas paged kernel (ops.paged_attention) that reads K/V blocks in
place via scalar-prefetched table indices instead of materializing
the gathered view — a trace-time branch, so the program key, its
signature and the zero-steady-state-compile contract are unchanged;
the ``use_paged_kernel`` guard still falls back to the XLA gather on
unsupported operands.
"""


def build_paged_fns(cfg, num_slots, block_size, num_blocks,
                    blocks_per_slot, sampling=False, attn_kernel=False):
    """(paged_prefill, paged_decode) for a GPT decode config. Pure and
    shape-stable; the engine AOT-compiles them (decode once, prefill
    once per tail bucket)."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ...ops import attention as attn_ops
    from ...ops import paged_attention as paged_attn_ops
    from ...text.models import _decode_forward_builder
    from ..sched.sampling import build_sampling_head

    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    hidden = cfg.hidden_size
    ln, forward_t = _decode_forward_builder(nh, hd, hidden)
    head = build_sampling_head(cfg.vocab_size) if sampling else None
    L = cfg.num_layers
    BS = int(block_size)
    MB = int(blocks_per_slot)
    C = MB * BS   # one slot's gathered contiguous context length

    def gather_slot(cache, bt_row):
        # [L, NB, nh, BS, hd] + row [MB] -> [L, 1, nh, MB*BS, hd],
        # position-ordered: view index bi*BS+off IS the cache position
        g = jnp.take(cache, bt_row, axis=1)          # [L, MB, nh, BS, hd]
        g = g.transpose(0, 2, 1, 3, 4).reshape(L, nh, C, hd)
        return g[:, None]

    def scatter_slot(cache, bt_row, view):
        # inverse of gather_slot; pad entries of bt_row all point at
        # the trash block (duplicate scatter indices land in garbage)
        blocks = view[:, 0].reshape(L, nh, MB, BS, hd) \
            .transpose(0, 2, 1, 3, 4)                # [L, MB, nh, BS, hd]
        return cache.at[:, bt_row].set(blocks)

    def _prefill_core(params, tokens, tail_len, start, slot, final,
                      bt_row, toks, pos, kc, vc, samp):
        # tokens [1, B] right-padded tail; start = cached prefix length
        kctx = gather_slot(kc, bt_row)
        vctx = gather_slot(vc, bt_row)
        logits, kctx, vctx = forward_t(params, tokens, start, kctx,
                                       vctx)
        kc = scatter_slot(kc, bt_row, kctx)
        vc = scatter_slot(vc, bt_row, vctx)
        last = jnp.take(logits[0], tail_len - 1, axis=0)   # [vocab]
        if samp is None:
            first = jnp.argmax(last, -1).astype(jnp.int32)
        else:
            seed, temp, topk, topp = samp
            first = head(last[None], seed[None],
                         (start + tail_len - 1)[None], temp[None],
                         topk[None], topp[None])[0]
        toks = jnp.where(final > 0, toks.at[slot].set(first), toks)
        # final: the next decode writes this slot at prompt_len;
        # interior chunk: park at the row's last addressable position
        pos = pos.at[slot].set(
            jnp.where(final > 0, start + tail_len, jnp.int32(C - 1)))
        return first[None], toks, pos, kc, vc

    if sampling:
        def paged_prefill(params, tokens, tail_len, start, slot,
                          final, bt_row, toks, pos, kc, vc, seed,
                          temp, topk, topp):
            return _prefill_core(params, tokens, tail_len, start,
                                 slot, final, bt_row, toks, pos, kc,
                                 vc, (seed, temp, topk, topp))
    else:
        def paged_prefill(params, tokens, tail_len, start, slot,
                          final, bt_row, toks, pos, kc, vc):
            return _prefill_core(params, tokens, tail_len, start,
                                 slot, final, bt_row, toks, pos, kc,
                                 vc, None)

    def _decode_core(params, toks, pos, tables, kc, vc, samp):
        S = toks.shape[0]
        x = params["wemb"][toks] + params["pemb"][
            jnp.minimum(pos, params["pemb"].shape[0] - 1)]  # [S, h]
        # clamp the WRITE position as a whole (column AND offset):
        # parked / released slots' positions keep incrementing past
        # the row, and clamping only the column would spray their
        # stray K/V across every offset of block MB-1 as pos % BS
        # cycles. Clamped, the stray write pins to the row's single
        # last entry (MB-1, BS-1) — always safe because the last row
        # block is never shared (only full-PROMPT blocks are indexed
        # for sharing, and acquire() guarantees at least one fresh
        # private block after the pinned prefix; pool.acquire asserts
        # this) and position C-1 is either trash-backed, beyond the
        # length mask, or legitimately rewritten before exposure.
        wpos = jnp.minimum(pos, jnp.int32(C - 1))
        col = wpos // jnp.int32(BS)
        bidx = jnp.take_along_axis(tables, col[:, None], axis=1)[:, 0]
        off = wpos % jnp.int32(BS)

        def body(carry, inp):
            x = carry
            p, kcl, vcl = inp
            h_ = ln(x, p["ln1_w"], p["ln1_b"])
            qkv = h_ @ p["qkv_w"] + p["qkv_b"]
            qkv = qkv.reshape(S, 3, nh, hd).transpose(1, 0, 2, 3)
            q, k, v = qkv[0], qkv[1], qkv[2]          # [S, nh, hd]
            # per-slot row write into its current (privately-owned)
            # block: advanced indexing [S],:,[S] scatters [S, nh, hd]
            kcl = kcl.at[bidx, :, off].set(k)
            vcl = vcl.at[bidx, :, off].set(v)
            if attn_kernel and paged_attn_ops.use_paged_kernel(q, kcl):
                o = paged_attn_ops.paged_decode_attention(
                    q, kcl, vcl, tables, pos + 1)
            else:
                o = attn_ops.cached_paged_attention(
                    q, kcl, vcl, tables, pos + 1)
            o = o.reshape(S, hidden)                  # concat heads
            x = x + (o @ p["out_w"] + p["out_b"])
            h2 = ln(x, p["ln2_w"], p["ln2_b"])
            m = jax.nn.gelu(h2 @ p["fc1_w"] + p["fc1_b"],
                            approximate=True)
            return x + (m @ p["fc2_w"] + p["fc2_b"]), (kcl, vcl)

        x, (kc, vc) = lax.scan(body, x, (params["stacked"], kc, vc))
        logits = ln(x, params["lnf_w"], params["lnf_b"]) \
            @ params["head"]                          # [S, vocab]
        if samp is None:
            nxt = jnp.argmax(logits, -1).astype(jnp.int32)
        else:
            seeds, temps, topks, topps = samp
            nxt = head(logits, seeds, pos, temps, topks, topps)
        return nxt, pos + jnp.int32(1), kc, vc

    if sampling:
        def paged_decode(params, toks, pos, tables, kc, vc, seeds,
                         temps, topks, topps):
            return _decode_core(params, toks, pos, tables, kc, vc,
                                (seeds, temps, topks, topps))
    else:
        def paged_decode(params, toks, pos, tables, kc, vc):
            return _decode_core(params, toks, pos, tables, kc, vc,
                                None)

    return paged_prefill, paged_decode
